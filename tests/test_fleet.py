"""Fleet front tier (serving/fleet.py, docs/SERVING.md#fleet).

Fast legs run against in-process STUB workers (stdlib HTTP servers with
canned behavior — no jax, no subprocesses): routing determinism and
rebalance bounds, header propagation across the proxy hop, failover /
502 / 503 contracts, rolling-reload ordering and version monotonicity,
metrics fan-in. The real-multi-process leg (archives → spawned
``fleet_worker`` processes → SIGKILL/reload under live HTTP) is
``slow``-marked — benchmarks/fleet_smoke.py runs the same contracts as a
CI smoke.
"""

import http.client
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from deeplearning4j_tpu.serving.fleet import (FleetRouter, affinity_key,
                                              fleet_spec, rendezvous_pick,
                                              rendezvous_score)

# ------------------------------------------------------------ pure hashing


class TestRendezvous:
    def test_deterministic_and_order_independent(self):
        key = affinity_key("bert", [5, 9, 1, 3, 3, 7, 2, 8], 8)
        members = ["w0", "w1", "w2", "w3"]
        pick = rendezvous_pick(key, members)
        for _ in range(50):
            assert rendezvous_pick(key, members) == pick
        assert rendezvous_pick(key, list(reversed(members))) == pick
        assert rendezvous_pick(key, ["w2", "w0", "w3", "w1"]) == pick

    def test_spreads_across_workers(self):
        members = ["w0", "w1", "w2", "w3"]
        counts = {m: 0 for m in members}
        for i in range(200):
            key = affinity_key("m", [i, i + 1, i * 3, 7], 4)
            counts[rendezvous_pick(key, members)] += 1
        # blake2b-scored HRW over 200 distinct keys: every worker owns a
        # real share (the deterministic keys above give ~50 each)
        assert all(c >= 20 for c in counts.values()), counts

    def test_rebalance_moves_only_the_lost_workers_keys(self):
        members = ["w0", "w1", "w2", "w3"]
        keys = [affinity_key("m", [i, 2 * i + 1, 13], 3)
                for i in range(300)]
        before = {k: rendezvous_pick(k, members) for k in keys}
        survivors = [m for m in members if m != "w2"]
        for k in keys:
            after = rendezvous_pick(k, survivors)
            if before[k] != "w2":
                # the HRW minimal-disruption bound: a surviving worker's
                # keys NEVER move when another worker leaves the ring —
                # its radix caches stay warm through a peer's death
                assert after == before[k]

    def test_affinity_key_semantics(self):
        # only the HEAD participates: divergence past `head` shares a key
        a = affinity_key("m", [1, 2, 3, 4, 99, 98], 4)
        b = affinity_key("m", [1, 2, 3, 4, 50, 51, 52], 4)
        assert a == b
        assert affinity_key("m", [1, 2, 3, 9], 4) != a
        assert affinity_key("other", [1, 2, 3, 4], 4) != a  # model-scoped
        assert affinity_key("m", [1, 2, 3, 4], 0) is None  # affinity off
        assert affinity_key("m", [], 4) is None            # no prompt
        assert affinity_key("m", None, 4) is None

    def test_score_is_not_python_hash(self):
        # process-salted hash() would break cross-process agreement; the
        # blake2b score is a fixed function — pin one value
        assert rendezvous_score(b"key", "w0") == \
            rendezvous_score(b"key", "w0")
        assert isinstance(rendezvous_score(b"key", "w0"), int)


# ------------------------------------------------------------ stub workers


class _StubWorker:
    """A canned worker: healthz/models/metrics plus configurable POST
    behavior. ``kill_posts`` aborts the connection on data-plane POSTs
    (the transport-failure case the router must fail over); ``behavior``
    maps verb -> (status, body_dict, extra_headers)."""

    def __init__(self):
        self.kill_posts = False
        self.shed = False
        self.draining = False
        self.version = 1
        self.reload_calls = []
        self.post_log = []
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def handle_error(self, *a):  # quiet aborted connections
                pass

            def _send(self, status, obj, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    status = 503 if stub.draining else 200
                    self._send(status, {
                        "status": "ok",
                        "serving": {"draining": stub.draining}})
                elif self.path == "/v1/models":
                    self._send(200, {
                        "draining": stub.draining,
                        "models": {"m": {"version": stub.version,
                                         "queue_depth": 0,
                                         "prefix_hit_rate": 0.5}}})
                elif self.path == "/metrics":
                    self._send(200, {})  # overridden below
                else:
                    self._send(404, {"error": "no route"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) if n else b"{}"
                if stub.kill_posts:
                    # transport failure: vanish without an HTTP response
                    self.connection.close()
                    raise ConnectionAbortedError
                rid = self.headers.get("X-Request-Id")
                stub.post_log.append((self.path, rid))
                if self.path.endswith("/reload"):
                    stub.version += 1
                    stub.reload_calls.append(
                        (time.monotonic(), json.loads(raw).get("path")))
                    self._send(200, {"model": "m",
                                     "version": stub.version})
                elif stub.shed:
                    # a worker-side 429: id + backoff hint must cross the
                    # router hop verbatim
                    self._send(429, {"error": "QueueFullError",
                                     "request_id": rid},
                               headers=[("Retry-After", "7"),
                                        ("X-Request-Id", rid or "")])
                else:
                    self._send(200, {"ok": True, "request_id": rid,
                                     "port": stub.port},
                               headers=[("X-Request-Id", rid or "")])

        # metrics needs text, not json — patch a real handler in
        def do_GET_metrics(handler):
            body = (b'# TYPE serving_queue_depth gauge\n'
                    b'serving_queue_depth{model="m"} 3\n'
                    b'up 1\n')
            handler.send_response(200)
            handler.send_header("Content-Type", "text/plain")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)

        orig_get = Handler.do_GET

        def do_GET(handler):
            if handler.path == "/metrics":
                do_GET_metrics(handler)
            else:
                orig_get(handler)

        Handler.do_GET = do_GET
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _post(port, path, body=None, headers=None, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        raw = json.dumps(body or {}).encode()
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", path, body=raw, headers=hdrs)
        r = conn.getresponse()
        data = r.read()
        return r.status, json.loads(data) if data else {}, dict(r.getheaders())
    finally:
        conn.close()


def _get(port, path, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read()
    finally:
        conn.close()


@pytest.fixture
def stub_fleet():
    stubs = [_StubWorker(), _StubWorker()]
    fleet = FleetRouter(adopt=[s.url for s in stubs],
                        health_interval_s=0.1, affinity_head=4,
                        name="stubfleet").start()
    yield fleet, stubs
    fleet.stop()
    for s in stubs:
        s.stop()


class TestStubFleet:
    def test_proxies_and_propagates_request_id(self, stub_fleet):
        fleet, stubs = stub_fleet
        st, body, hdrs = _post(fleet.port, "/v1/models/m/infer",
                               {"inputs": [[1.0]]},
                               headers={"X-Request-Id": "caller-id-42"})
        assert st == 200
        # the caller's id crossed BOTH hops verbatim — never re-minted
        assert hdrs.get("X-Request-Id") == "caller-id-42"
        assert body["request_id"] == "caller-id-42"
        rids = [r for _p, r in stubs[0].post_log + stubs[1].post_log]
        assert rids == ["caller-id-42"]

    def test_mints_request_id_when_absent(self, stub_fleet):
        fleet, _stubs = stub_fleet
        st, _body, hdrs = _post(fleet.port, "/v1/models/m/infer", {})
        assert st == 200
        assert hdrs.get("X-Request-Id")  # minted at the front tier

    def test_retry_after_crosses_the_hop_verbatim(self, stub_fleet):
        fleet, stubs = stub_fleet
        for s in stubs:
            s.shed = True
        st, body, hdrs = _post(fleet.port, "/v1/models/m/infer", {},
                               headers={"X-Request-Id": "shed-1"})
        assert st == 429
        # the worker's backoff hint and the caller's id both survive the
        # router hop unmodified (the satellite bugfix contract)
        assert hdrs.get("Retry-After") == "7"
        assert hdrs.get("X-Request-Id") == "shed-1"

    def test_affinity_same_head_same_worker(self, stub_fleet):
        fleet, stubs = stub_fleet
        ports = set()
        for _ in range(6):
            st, body, _h = _post(
                fleet.port, "/v1/models/m/generate",
                {"prompt_tokens": [3, 1, 4, 1, 5, 9], "max_new_tokens": 2})
            assert st == 200
            ports.add(body["port"])
        assert len(ports) == 1  # every shared-head request: one worker
        assert fleet.status()["routing_decisions"]["affinity"] >= 6

    def test_failover_on_connection_failure(self, stub_fleet):
        fleet, stubs = stub_fleet
        # find which stub owns this prompt head, then break it
        st, body, _h = _post(fleet.port, "/v1/models/m/generate",
                             {"prompt_tokens": [2, 7, 1, 8]})
        owner = next(s for s in stubs if s.port == body["port"])
        owner.kill_posts = True
        st, body, _h = _post(fleet.port, "/v1/models/m/generate",
                             {"prompt_tokens": [2, 7, 1, 8]})
        assert st == 200  # failed over to the live worker
        assert body["port"] != owner.port
        assert fleet.status()["routing_decisions"]["failover"] >= 1

    def test_502_when_every_worker_fails_transport(self, stub_fleet):
        fleet, stubs = stub_fleet
        for s in stubs:
            s.kill_posts = True
        st, body, _h = _post(fleet.port, "/v1/models/m/infer", {})
        assert st == 502
        assert body["error"] == "WorkerProxyError"

    def test_503_with_retry_after_when_ring_empty(self, stub_fleet):
        fleet, stubs = stub_fleet
        for s in stubs:
            s.draining = True
        deadline = time.monotonic() + 5
        while fleet._ring() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not fleet._ring()
        st, body, hdrs = _post(fleet.port, "/v1/models/m/infer", {})
        assert st == 503
        assert body["error"] == "FleetUnavailableError"
        assert int(hdrs.get("Retry-After", 0)) >= 1
        st, _data = _get(fleet.port, "/healthz")
        assert st == 503  # fleet healthz follows the ring

    def test_draining_worker_leaves_ring_without_dropping_fleet(
            self, stub_fleet):
        fleet, stubs = stub_fleet
        stubs[0].draining = True
        deadline = time.monotonic() + 5
        while len(fleet._ring()) != 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(fleet._ring()) == 1
        st, body, _h = _post(fleet.port, "/v1/models/m/infer", {})
        assert st == 200
        assert body["port"] == stubs[1].port

    def test_rolling_reload_sequential_and_monotone(self, stub_fleet):
        fleet, stubs = stub_fleet
        st, body, _h = _post(fleet.port, "/v1/models/m/reload",
                             {"path": "/tmp/new.zip"})
        assert st == 200
        assert sorted(body["versions"]) == ["w0", "w1"]
        assert all(v == 2 for v in body["versions"].values())
        # worker-by-worker: the second worker's reload STARTED after the
        # first one's completed (timestamps recorded at response time)
        times = sorted(t for s in stubs for (t, _p) in s.reload_calls)
        assert len(times) == 2
        for s in stubs:
            assert s.reload_calls[0][1] == "/tmp/new.zip"
        # versions advance monotonically on a second roll
        st, body2, _h = _post(fleet.port, "/v1/models/m/reload",
                              {"path": "/tmp/new2.zip"})
        assert all(v == 3 for v in body2["versions"].values())

    def test_fleet_status_route(self, stub_fleet):
        fleet, stubs = stub_fleet
        st, data = _get(fleet.port, "/v1/fleet")
        assert st == 200
        doc = json.loads(data)
        assert doc["ring"] == ["w0", "w1"]
        assert doc["affinity_head"] == 4
        for wid in ("w0", "w1"):
            w = doc["workers"][wid]
            assert w["in_ring"] and w["healthy"] and w["adopted"]
            assert w["models"]["m"]["prefix_cache_hit_rate"] == 0.5

    def test_metrics_fan_in_relabels_per_worker(self, stub_fleet):
        fleet, _stubs = stub_fleet
        _post(fleet.port, "/v1/models/m/infer", {})  # one routed request
        st, data = _get(fleet.port, "/metrics")
        assert st == 200
        text = data.decode()
        # worker series re-exported with the worker label injected; bare
        # series get one minted
        assert 'serving_queue_depth{worker="w0",model="m"} 3' in text
        assert 'serving_queue_depth{worker="w1",model="m"} 3' in text
        assert 'up{worker="w0"} 1' in text
        # the router's own registry: routing decisions + ring gauges
        assert "serving_fleet_routing_decisions_total" in text
        assert 'serving_fleet_ring_size{fleet="stubfleet"} 2' in text
        # worker comment lines were stripped (one scrape = one parse)
        assert text.count("# TYPE serving_queue_depth gauge") == 0

    def test_404_route_contract(self, stub_fleet):
        fleet, _stubs = stub_fleet
        st, body, _h = _post(fleet.port, "/v1/models/m/nope", {})
        assert st == 404


# ------------------------------------------------------ real process leg


@pytest.mark.slow
class TestRealFleet:
    """The tests/_dist_worker.py-style leg: real spawned worker processes,
    real HTTP, real SIGKILL. One fleet boot amortized across contracts;
    benchmarks/fleet_smoke.py re-runs these under CI load."""

    @pytest.fixture(scope="class")
    def fleet_env(self, tmp_path_factory):
        import numpy as np

        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        tmp = tmp_path_factory.mktemp("fleet")

        def dense(seed):
            conf = (NeuralNetConfiguration.builder().seed(seed)
                    .updater(Adam(1e-3)).batch_buckets((1, 2, 4)).list()
                    .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
                    .layer(OutputLayer(n_in=16, n_out=4, loss="mcxent",
                                       activation="softmax"))
                    .set_input_type(InputType.feed_forward(8)).build())
            return MultiLayerNetwork(conf).init()

        net = dense(0)
        path = str(tmp / "clf.zip")
        ModelSerializer.write_model(net, path, save_updater=False)
        spec = fleet_spec(
            models=[{"id": "clf", "path": path, "kind": "classify",
                     "register": {"max_wait_ms": 1.0,
                                  "queue_limit": 128}}],
            env={"JAX_PLATFORMS": "cpu"})
        fleet = FleetRouter(spec, n_workers=2, health_interval_s=0.2,
                            name="testfleet").start()
        x = np.random.RandomState(3).normal(size=(2, 8)) \
            .astype(np.float32)
        yield {"fleet": fleet, "net": net, "x": x, "tmp": tmp,
               "dense": dense, "np": np}
        fleet.stop()

    def test_http_identical_to_inprocess_oracle(self, fleet_env):
        fleet, net, x, np = (fleet_env["fleet"], fleet_env["net"],
                             fleet_env["x"], fleet_env["np"])
        oracle = np.asarray(net.output(x))
        for _ in range(4):
            st, body, hdrs = _post(fleet.port, "/v1/models/clf/infer",
                                   {"inputs": x.tolist()},
                                   headers={"X-Request-Id": "oracle-1"})
            assert st == 200
            assert hdrs.get("X-Request-Id") == "oracle-1"
            assert np.allclose(np.asarray(body["outputs"]), oracle,
                               atol=1e-5)

    def test_sigkill_failover_and_respawn(self, fleet_env):
        fleet, x = fleet_env["fleet"], fleet_env["x"]
        victim = fleet._ring()[0]
        os.kill(victim.pid, 9)
        ok = 0
        for _ in range(8):
            st, _body, _h = _post(fleet.port, "/v1/models/clf/infer",
                                  {"inputs": x.tolist()}, timeout=30)
            ok += st == 200
        assert ok == 8  # zero loss: requests failed over mid-kill
        deadline = time.monotonic() + 120
        while len(fleet._ring()) < 2 and time.monotonic() < deadline:
            time.sleep(0.25)
        assert len(fleet._ring()) == 2  # respawned + re-entered the ring
        assert fleet.worker(victim.worker_id).restarts >= 1

    def test_rolling_reload_under_live_traffic(self, fleet_env):
        fleet, x, np = fleet_env["fleet"], fleet_env["x"], fleet_env["np"]
        net2 = fleet_env["dense"](7)
        path2 = str(fleet_env["tmp"] / "clf2.zip")
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        ModelSerializer.write_model(net2, path2, save_updater=False)
        stop = threading.Event()
        failures = []

        def traffic():
            while not stop.is_set():
                st, _b, _h = _post(fleet.port, "/v1/models/clf/infer",
                                   {"inputs": x.tolist()}, timeout=30)
                if st != 200:
                    failures.append(st)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            st, body, _h = _post(fleet.port, "/v1/models/clf/reload",
                                 {"path": path2}, timeout=300)
        finally:
            stop.set()
            t.join(timeout=30)
        assert st == 200
        versions = body["versions"]
        assert sorted(versions) == ["w0", "w1"]
        assert all(v >= 2 for v in versions.values())
        assert not failures  # zero fleet-level shed during the roll
        st, body, _h = _post(fleet.port, "/v1/models/clf/infer",
                             {"inputs": x.tolist()}, timeout=30)
        oracle2 = np.asarray(net2.output(x))
        assert np.allclose(np.asarray(body["outputs"]), oracle2,
                           atol=1e-5)
