"""Op-table tests — registry semantics + array-op correctness vs numpy.

Models the reference's Nd4jTestsC / CustomOpsTests suites (SURVEY.md §4): op
semantics validated against an independent reference implementation (numpy),
plus registry/dispatch behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import ops


def test_registry_size_and_categories():
    assert ops.op_count() > 200, f"op table too small: {ops.op_count()}"
    cats = ops.categories()
    for family in [
        "transform_float", "transform_same", "pairwise", "scalar", "reduce",
        "indexreduce", "summarystats", "reduce3", "linalg", "conv", "pooling",
        "norm", "loss", "random", "shape", "gather_scatter", "attention",
    ]:
        assert family in cats, f"missing op family {family}"


def test_alias_resolution():
    assert ops.get_op("mmul") is ops.get_op("matmul")
    assert ops.get_op("silu") is ops.get_op("swish")
    assert ops.has_op("old_mul")
    with pytest.raises(ops.OpNotFoundError):
        ops.get_op("no_such_op_xyz")


def test_exec_by_name_matches_direct_call(rng):
    x = jnp.asarray(rng.standard_normal((4, 5)), dtype=jnp.float32)
    np.testing.assert_allclose(ops.exec_op("exp", x), np.exp(np.asarray(x)), rtol=1e-6)
    np.testing.assert_allclose(
        ops.exec_op("sum", x, axis=1), np.asarray(x).sum(axis=1), rtol=1e-6
    )


def test_exec_op_traceable_under_jit(rng):
    x = jnp.asarray(rng.standard_normal((8, 8)), dtype=jnp.float32)

    @jax.jit
    def f(x):
        y = ops.exec_op("multiply", x, x)
        return ops.exec_op("sum", y)

    np.testing.assert_allclose(f(x), (np.asarray(x) ** 2).sum(), rtol=1e-5)


def test_shape_inference_without_execution():
    x = jax.ShapeDtypeStruct((32, 128), jnp.float32)
    w = jax.ShapeDtypeStruct((128, 64), jnp.float32)
    out = ops.shape_of("matmul", x, w)
    assert out.shape == (32, 64)
    assert out.dtype == jnp.float32


UNARY_CASES = [
    ("exp", np.exp), ("log1p", np.log1p), ("sqrt", np.sqrt), ("tanh", np.tanh),
    ("abs", np.abs), ("floor", np.floor), ("square", np.square),
    ("sign", np.sign), ("neg", np.negative),
]


@pytest.mark.parametrize("name,ref", UNARY_CASES)
def test_unary_transforms(name, ref, rng):
    x = np.abs(rng.standard_normal((3, 7)).astype(np.float32)) + 0.1
    np.testing.assert_allclose(ops.exec_op(name, jnp.asarray(x)), ref(x), rtol=1e-5)


BINARY_CASES = [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
    ("pow", np.power),
]


@pytest.mark.parametrize("name,ref", BINARY_CASES)
def test_pairwise_with_broadcasting(name, ref, rng):
    x = np.abs(rng.standard_normal((4, 1, 5)).astype(np.float32)) + 0.5
    y = np.abs(rng.standard_normal((3, 1)).astype(np.float32)) + 0.5
    np.testing.assert_allclose(
        ops.exec_op(name, jnp.asarray(x), jnp.asarray(y)), ref(x, y), rtol=1e-5
    )


def test_reductions(rng):
    x = rng.standard_normal((6, 4, 5)).astype(np.float32)
    jx = jnp.asarray(x)
    np.testing.assert_allclose(ops.exec_op("mean", jx, axis=(0, 2)), x.mean(axis=(0, 2)), rtol=1e-5)
    np.testing.assert_allclose(ops.exec_op("norm2", jx, axis=1), np.linalg.norm(x, axis=1), rtol=1e-5)
    np.testing.assert_allclose(ops.exec_op("argmax", jx, axis=-1), x.argmax(axis=-1))
    # ND4J variance defaults to bias-corrected (ddof=1).
    np.testing.assert_allclose(ops.exec_op("var", jx, axis=0), x.var(axis=0, ddof=1), rtol=1e-4)


def test_reduce3_distances(rng):
    x = rng.standard_normal((10,)).astype(np.float32)
    y = rng.standard_normal((10,)).astype(np.float32)
    np.testing.assert_allclose(
        ops.exec_op("euclidean", jnp.asarray(x), jnp.asarray(y)),
        np.linalg.norm(x - y), rtol=1e-5,
    )
    cos = np.dot(x, y) / (np.linalg.norm(x) * np.linalg.norm(y))
    np.testing.assert_allclose(
        ops.exec_op("cosinesimilarity", jnp.asarray(x), jnp.asarray(y)), cos, rtol=1e-5
    )


def test_matmul_bf16_accumulates_fp32():
    # bf16 inputs with fp32 accumulation should beat naive bf16 accumulation.
    k = 4096
    a = jnp.full((1, k), 0.01, dtype=jnp.bfloat16)
    b = jnp.ones((k, 1), dtype=jnp.bfloat16)
    out = ops.exec_op("matmul", a, b)
    assert out.dtype == jnp.bfloat16
    assert abs(float(out[0, 0]) - k * 0.01) / (k * 0.01) < 0.01


def test_gather_scatter(rng):
    x = rng.standard_normal((5, 3)).astype(np.float32)
    idx = np.array([0, 2, 4])
    np.testing.assert_allclose(ops.exec_op("gather", jnp.asarray(x), jnp.asarray(idx)), x[idx])
    upd = np.ones((3, 3), dtype=np.float32)
    out = ops.exec_op("scatter_add", jnp.asarray(x), jnp.asarray(idx), jnp.asarray(upd))
    expect = x.copy()
    expect[idx] += 1.0
    np.testing.assert_allclose(out, expect)


def test_one_hot():
    out = ops.exec_op("onehot", jnp.array([0, 2, 1]), 4)
    expect = np.eye(4, dtype=np.float32)[[0, 2, 1]]
    np.testing.assert_allclose(out, expect)


def test_concat_stack_split(rng):
    x = rng.standard_normal((2, 3)).astype(np.float32)
    y = rng.standard_normal((2, 3)).astype(np.float32)
    np.testing.assert_allclose(
        ops.exec_op("concat", [jnp.asarray(x), jnp.asarray(y)], axis=0),
        np.concatenate([x, y], axis=0),
    )
    np.testing.assert_allclose(
        ops.exec_op("stack", [jnp.asarray(x), jnp.asarray(y)], axis=1),
        np.stack([x, y], axis=1),
    )
    parts = ops.exec_op("split", jnp.asarray(x), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1)


def test_random_ops_reproducible(key):
    a = ops.exec_op("random_normal", key, (16, 16))
    b = ops.exec_op("random_normal", key, (16, 16))
    np.testing.assert_array_equal(a, b)
    k1, k2 = ops.exec_op("random_split_key", key)
    c = ops.exec_op("random_normal", k1, (16, 16))
    assert not np.allclose(a, c)


def test_dropout_train_vs_inference(key):
    x = jnp.ones((1000,))
    out_inf = ops.exec_op("dropout", x, key, 0.5, training=False)
    np.testing.assert_array_equal(out_inf, x)
    out_tr = ops.exec_op("dropout", x, key, 0.5, training=True)
    # Inverted dropout preserves the mean.
    assert abs(float(out_tr.mean()) - 1.0) < 0.15
    kept = float((out_tr != 0).mean())
    assert 0.4 < kept < 0.6


def test_topk(rng):
    x = rng.standard_normal((4, 10)).astype(np.float32)
    vals, idx = ops.exec_op("top_k", jnp.asarray(x), 3)
    np.testing.assert_allclose(vals, np.sort(x, axis=-1)[:, ::-1][:, :3], rtol=1e-6)


def test_fmod_vs_mod_negative_operands():
    # C fmod: sign follows dividend; python mod: sign follows divisor
    np.testing.assert_allclose(ops.exec_op("fmod", jnp.array(-7.0), jnp.array(3.0)), -1.0)
    np.testing.assert_allclose(ops.exec_op("mod", jnp.array(-7.0), jnp.array(3.0)), 2.0)


def test_one_hot_integer_dtype():
    out = ops.exec_op("onehot", jnp.array([0, 2]), 4, dtype=jnp.int32)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(out, np.eye(4, dtype=np.int32)[[0, 2]])


def test_dynamic_stitch_tf_semantics():
    out = ops.exec_op(
        "dynamic_stitch",
        [jnp.array([0, 1]), jnp.array([1])],
        [jnp.array([[1.0], [2.0]]), jnp.array([[9.0]])],
    )
    assert out.shape == (2, 1)
    np.testing.assert_allclose(out, [[1.0], [9.0]])


def test_logsumexp_handles_neg_inf():
    x = jnp.array([-jnp.inf, 0.0])
    np.testing.assert_allclose(ops.exec_op("logsumexp", x), 0.0, atol=1e-6)


class TestRound3Ops:
    """space_to_batch_nd set, sequence ops, SRU, fused ConvLSTM
    (VERDICT r2 next-round #9)."""

    def test_space_to_batch_roundtrip(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 6, 4, 3)).astype(np.float32))
        y = ops.exec_op("space_to_batch", x, (2, 2), [[0, 0], [0, 0]])
        assert y.shape == (8, 3, 2, 3)
        back = ops.exec_op("batch_to_space", y, (2, 2), [[0, 0], [0, 0]])
        np.testing.assert_allclose(back, x)

    def test_space_to_batch_matches_tf(self, rng):
        tf = __import__("pytest").importorskip("tensorflow")
        x = rng.normal(size=(1, 5, 5, 2)).astype(np.float32)
        for bs, pads in (((2, 2), [[1, 0], [0, 1]]), ((3, 1), [[1, 0], [0, 0]])):
            want = np.asarray(tf.raw_ops.SpaceToBatchND(
                input=x, block_shape=list(bs), paddings=pads))
            got = np.asarray(ops.exec_op("space_to_batch", x, bs, pads))
            np.testing.assert_allclose(got, want)
            round_ = np.asarray(tf.raw_ops.BatchToSpaceND(
                input=want, block_shape=list(bs), crops=pads))
            back = np.asarray(ops.exec_op("batch_to_space", got, bs, pads))
            np.testing.assert_allclose(back, round_)

    def test_sequence_mask(self):
        m = ops.exec_op("sequence_mask", jnp.asarray([1, 3, 0]), 4)
        np.testing.assert_array_equal(
            np.asarray(m),
            [[1, 0, 0, 0], [1, 1, 1, 0], [0, 0, 0, 0]])

    def test_sru_cell_and_layer_consistent(self, rng):
        """Scanning sru_cell step-by-step equals the whole-sequence op."""
        B, T, I = 2, 5, 4
        x = jnp.asarray(rng.normal(size=(B, T, I)).astype(np.float32))
        W = jnp.asarray(rng.normal(size=(3 * I, I)).astype(np.float32) * 0.3)
        b = jnp.asarray(rng.normal(size=(2 * I,)).astype(np.float32) * 0.1)
        h_seq, c_fin = ops.exec_op("sru", x, W, b)
        c = jnp.zeros((B, I))
        for t in range(T):
            h_t, c = ops.exec_op("sru_cell", x[:, t], c, W, b)
            np.testing.assert_allclose(np.asarray(h_seq[:, t]),
                                       np.asarray(h_t), atol=1e-5)
        np.testing.assert_allclose(np.asarray(c_fin), np.asarray(c), atol=1e-5)

    def test_sru_mask_freezes_state(self, rng):
        B, T, I = 2, 4, 3
        x = jnp.asarray(rng.normal(size=(B, T, I)).astype(np.float32))
        W = jnp.asarray(rng.normal(size=(3 * I, I)).astype(np.float32) * 0.3)
        b = jnp.zeros((2 * I,))
        mask = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], jnp.float32)
        h, c_fin = ops.exec_op("sru", x, W, b, mask=mask)
        np.testing.assert_allclose(np.asarray(h[0, 2:]), 0.0)  # masked out
        # state frozen at the mask boundary for row 0
        h2, c2 = ops.exec_op("sru", x[:, :2], W, b)
        np.testing.assert_allclose(np.asarray(c_fin[0]), np.asarray(c2[0]),
                                   atol=1e-6)

    def test_conv_lstm_2d_matches_layer(self, rng):
        """The registry op and the nn ConvLSTM2D layer share semantics."""
        from deeplearning4j_tpu.nn.recurrent import ConvLSTM2D
        import jax

        lyr = ConvLSTM2D(n_in=2, n_out=3, kernel_size=(3, 3),
                         padding="SAME", return_sequences=True,
                         forget_gate_bias_init=0.0)
        params, _ = lyr.initialize(jax.random.PRNGKey(0), (4, 5, 5, 2))
        x = jnp.asarray(rng.normal(size=(2, 4, 5, 5, 2)).astype(np.float32))
        y_layer, _ = lyr.apply(params, {}, x)
        y_op, _ = ops.exec_op("conv_lstm_2d", x, params["W"], params["U"],
                              params["b"])
        np.testing.assert_allclose(np.asarray(y_op), np.asarray(y_layer),
                                   atol=1e-5)

    def test_conv_lstm_2d_h0_without_c0(self, rng):
        """c defaults to zeros independently of a provided h0 (review fix)."""
        import jax
        x = jnp.asarray(rng.normal(size=(1, 2, 4, 4, 2)).astype(np.float32))
        W = jnp.asarray(rng.normal(size=(3, 3, 2, 12)).astype(np.float32) * 0.2)
        U = jnp.asarray(rng.normal(size=(3, 3, 3, 12)).astype(np.float32) * 0.2)
        h0 = jnp.ones((1, 4, 4, 3))
        y_a, (_, c_a) = ops.exec_op("conv_lstm_2d", x, W, U, h0=h0)
        y_b, (_, c_b) = ops.exec_op("conv_lstm_2d", x, W, U, h0=h0,
                                    c0=jnp.zeros_like(h0))
        np.testing.assert_allclose(np.asarray(y_a), np.asarray(y_b), atol=1e-6)


class TestLongTailOps:
    """Round-3 registry push beyond the named families (registry 377)."""

    def test_unsorted_segment_family(self):
        data = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        ids = jnp.asarray([1, 0, 1])
        s = ops.exec_op("unsorted_segment_sum", data, ids, 2)
        np.testing.assert_allclose(np.asarray(s), [[3, 4], [6, 8]])
        m = ops.exec_op("unsorted_segment_mean", data, ids, 2)
        np.testing.assert_allclose(np.asarray(m), [[3, 4], [3, 4]])
        p = ops.exec_op("unsorted_segment_prod", data, ids, 2)
        np.testing.assert_allclose(np.asarray(p), [[3, 4], [5, 12]])

    def test_unique_with_counts_and_listdiff(self):
        v, c = ops.exec_op("unique_with_counts", jnp.asarray([3, 1, 3, 2, 3]))
        np.testing.assert_array_equal(np.asarray(v), [1, 2, 3])
        np.testing.assert_array_equal(np.asarray(c), [1, 1, 3])
        vals, idx = ops.exec_op("listdiff", jnp.asarray([1, 2, 3, 4, 5]),
                                jnp.asarray([2, 4]))
        np.testing.assert_array_equal(np.asarray(vals), [1, 3, 5])
        np.testing.assert_array_equal(np.asarray(idx), [0, 2, 4])

    def test_cumlogsumexp_matches_numpy(self, rng):
        x = jnp.asarray(rng.normal(size=(5, 3)).astype(np.float32))
        got = np.asarray(ops.exec_op("cumlogsumexp", x, axis=0))
        want = np.logaddexp.accumulate(np.asarray(x), axis=0)
        # TPU transcendentals are ~1e-4-accurate; exact on CPU
        np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)
        ex = np.asarray(ops.exec_op("cumlogsumexp", x, axis=0,
                                    exclusive=True))
        assert np.all(np.isneginf(ex[0]))
        np.testing.assert_allclose(ex[1:], want[:-1], atol=5e-4, rtol=1e-4)

    def test_weighted_xent_matches_tf(self, rng):
        tf = __import__("pytest").importorskip("tensorflow")
        t = (rng.random((3, 4)) > 0.5).astype(np.float32)
        l = rng.normal(size=(3, 4)).astype(np.float32)
        want = tf.nn.weighted_cross_entropy_with_logits(
            labels=t, logits=l, pos_weight=2.0).numpy()
        got = np.asarray(ops.exec_op(
            "weighted_cross_entropy_with_logits", t, l, 2.0))
        np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)

    def test_col2im_adjoint_of_im2col(self, rng):
        """<im2col(x), p> == <x, col2im(p)> — exact adjointness."""
        x = jnp.asarray(rng.normal(size=(1, 4, 4, 2)).astype(np.float32))
        patches = ops.exec_op("im2col", x, (2, 2))
        p = jnp.asarray(rng.normal(size=patches.shape).astype(np.float32))
        lhs = float(jnp.sum(patches * p))
        back = ops.exec_op("col2im", p, x.shape, (2, 2))
        rhs = float(jnp.sum(x * back))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-4)

    def test_clip_by_global_norm(self, rng):
        a = jnp.asarray(rng.normal(size=(4,)).astype(np.float32)) * 10
        b = jnp.asarray(rng.normal(size=(3,)).astype(np.float32)) * 10
        clipped, gn = ops.exec_op("clip_by_global_norm", [a, b], 1.0)
        got = float(jnp.sqrt(sum(jnp.sum(c * c) for c in clipped)))
        np.testing.assert_allclose(got, 1.0, rtol=1e-4)
        np.testing.assert_allclose(
            float(gn), float(jnp.sqrt(jnp.sum(a * a) + jnp.sum(b * b))),
            rtol=1e-5)

    def test_entropy_family(self):
        p = jnp.asarray([0.5, 0.25, 0.25, 0.0])
        e = float(ops.exec_op("entropy", p))
        np.testing.assert_allclose(e, 1.5 * np.log(2.0), rtol=1e-5)
        sh = float(ops.exec_op("shannon_entropy", p))
        np.testing.assert_allclose(sh, 1.5, rtol=1e-5)
        le = float(ops.exec_op("log_entropy", p))
        np.testing.assert_allclose(le, np.log(1.5 * np.log(2.0)), rtol=1e-5)

    def test_sparse_to_dense_and_scatter(self):
        d = ops.exec_op("sparse_to_dense", jnp.asarray([[0, 1], [1, 0]]),
                        (2, 2), jnp.asarray([5.0, 7.0]), default_value=-1.0)
        np.testing.assert_allclose(np.asarray(d), [[-1, 5], [7, -1]])
        u = ops.exec_op("tensor_scatter_update", jnp.zeros((3, 2)),
                        jnp.asarray([[2]]), jnp.asarray([[9.0, 9.0]]))
        np.testing.assert_allclose(np.asarray(u)[2], [9, 9])

    def test_bit_ops(self):
        x = jnp.asarray([1, 2], jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(ops.exec_op("toggle_bits", x)), [-2, -3])
        r = ops.exec_op("cyclic_shift_bits", jnp.asarray([1], jnp.int32), 33)
        np.testing.assert_array_equal(np.asarray(r), [2])  # rot by 33 == 1

    def test_divide_no_nan(self):
        out = ops.exec_op("divide_no_nan", jnp.asarray([1.0, 2.0]),
                          jnp.asarray([0.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out), [0.0, 1.0])

    def test_cyclic_shift_signed_dtypes(self):
        """Rotations on signed ints must not sign-extend (review fix)."""
        r = ops.exec_op("cyclic_shift_bits", jnp.asarray([-127], jnp.int8), 1)
        np.testing.assert_array_equal(np.asarray(r), [3])  # 0b10000001 rotl 1
        r0 = ops.exec_op("cyclic_shift_bits", jnp.asarray([-5], jnp.int16), 16)
        np.testing.assert_array_equal(np.asarray(r0), [-5])  # full-width = id

    def test_cyclic_shift_array_count_no_promotion(self):
        """Array-valued counts wider than x must not widen the bit view
        (review fix): output keeps x's shape and dtype."""
        r = ops.exec_op("cyclic_shift_bits", jnp.asarray([1, 1], jnp.int16),
                        jnp.asarray([1, 2], jnp.int32))
        assert r.shape == (2,) and r.dtype == jnp.int16
        np.testing.assert_array_equal(np.asarray(r), [2, 4])


class TestRound4OpTail:
    """VERDICT r3 missing #4 / next-round #10: merge ops, ssim, hardswish."""

    def test_merge_family(self, rng):
        from deeplearning4j_tpu.ops import registry

        a, b, c = (rng.standard_normal((3, 4)).astype(np.float32)
                   for _ in range(3))
        np.testing.assert_allclose(
            np.asarray(registry.exec_op("mergeadd", a, b, c)), a + b + c,
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(registry.exec_op("mergeavg", a, b, c)),
            (a + b + c) / 3, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(registry.exec_op("mergemax", a, b, c)),
            np.maximum(np.maximum(a, b), c), rtol=1e-6)

    def test_ssim_matches_tf(self, rng):
        tf = pytest.importorskip("tensorflow")
        from deeplearning4j_tpu.ops import registry

        a = rng.random((2, 32, 32, 3)).astype(np.float32)
        b = np.clip(a + rng.normal(size=a.shape).astype(np.float32) * 0.05,
                    0, 1).astype(np.float32)
        ours = np.asarray(registry.exec_op("ssim", a, b))
        golden = tf.image.ssim(tf.constant(a), tf.constant(b),
                               max_val=1.0).numpy()
        np.testing.assert_allclose(ours, golden, atol=1e-5)

    def test_hardswish_matches_torch(self, rng):
        torch = pytest.importorskip("torch")
        from deeplearning4j_tpu.ops import registry

        x = rng.standard_normal(32).astype(np.float32)
        ours = np.asarray(registry.exec_op("hardswish", x))
        golden = torch.nn.functional.hardswish(torch.tensor(x)).numpy()
        np.testing.assert_allclose(ours, golden, atol=1e-6)
