"""Distributed training: compression ops, accumulator, training masters.

Reference test parity: the Spark-master tests run on local[N] in-process and
parameter-server tests on embedded loopback transport (SURVEY.md §4,
"distributed without a cluster") — here the 8-virtual-device CPU mesh plays
that role.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import compression as C
from deeplearning4j_tpu.parallel import (
    AdaptiveThresholdAlgorithm,
    EncodedGradientsAccumulator,
    FixedThresholdAlgorithm,
    ParameterAveragingTrainingMaster,
    ResidualClippingPostProcessor,
    SharedTrainingMaster,
    SparkDl4jMultiLayer,
    TrainingMesh,
    distributed,
)


class TestCompressionOps:
    def test_threshold_roundtrip_with_residual(self, rng):
        g = jnp.asarray(rng.normal(size=(64,)) * 0.01, jnp.float32)
        q, r = C.threshold_encode(g, 1e-2)
        np.testing.assert_allclose(q + r, g, atol=1e-7)
        assert set(np.unique(np.abs(np.asarray(q)))) <= {0.0, np.float32(1e-2)}

    def test_bitmap_roundtrip(self, rng):
        g = jnp.asarray(rng.normal(size=(50,)) * 0.01, jnp.float32)
        packed, residual = C.bitmap_encode(g, 1e-2)
        dec = C.bitmap_decode(packed, 1e-2, (50,))
        np.testing.assert_allclose(dec + residual, g, atol=1e-7)

    def test_sparse_pack_unpack(self, rng):
        g = jnp.asarray(rng.normal(size=(40,)) * 0.01, jnp.float32)
        q, _ = C.threshold_encode(g, 1e-2)
        msg = C.sparse_pack(np.asarray(q), 1e-2)
        back = C.sparse_unpack(msg, 1e-2, (40,))
        np.testing.assert_allclose(back, q, atol=1e-7)
        assert msg.size == int((np.asarray(q) != 0).sum())


class TestAccumulator:
    def test_error_feedback_preserves_signal(self, rng):
        acc = EncodedGradientsAccumulator(
            threshold_algorithm=FixedThresholdAlgorithm(1e-2),
            residual_post_processor=None)
        g = {"w": jnp.asarray(rng.normal(size=(32,)) * 0.005, jnp.float32)}
        residual = acc.init_residual(g)
        t = acc.threshold_algorithm.init_state()
        total = jnp.zeros((32,))
        for it in range(50):
            quant, residual, t, _ = acc.encode(g, residual, t, it)
            total = total + quant["w"]
        # over many steps the transmitted sum approaches the true sum (error
        # feedback: nothing is lost, only delayed)
        np.testing.assert_allclose(total / 50, g["w"], atol=1.2e-2)

    def test_adaptive_threshold_moves_toward_target(self):
        algo = AdaptiveThresholdAlgorithm(initial=1e-3, target_ratio=0.1)
        t = algo.init_state()
        t_dense = algo.update(t, jnp.asarray(0.9))   # too dense → raise t
        t_sparse = algo.update(t, jnp.asarray(0.001))  # too sparse → lower t
        assert float(t_dense) > float(t) > float(t_sparse)

    def test_residual_clipping(self):
        pp = ResidualClippingPostProcessor(max_multiplier=2.0, frequency=1)
        r = {"w": jnp.asarray([10.0, -10.0, 0.5])}
        out = pp.apply(r, jnp.asarray(1.0), jnp.asarray(0))
        np.testing.assert_allclose(out["w"], [2.0, -2.0, 0.5])


def _classifier_and_data(rng, n=256):
    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import (
        InputType,
        MultiLayerNetwork,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(Adam(0.01))
        .list()
        .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
        .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    centers = rng.standard_normal((3, 4)) * 3.0
    ys = rng.integers(0, 3, n)
    xs = (centers[ys] + rng.standard_normal((n, 4))).astype(np.float32)
    yoh = np.eye(3, dtype=np.float32)[ys]
    return net, ArrayDataSetIterator(xs, yoh, batch=64), xs, yoh


@pytest.mark.multichip
class TestTrainingMasters:
    def test_parameter_averaging_learns(self, rng):
        net, it, xs, ys = _classifier_and_data(rng)
        master = ParameterAveragingTrainingMaster(
            averaging_frequency=2, mesh=TrainingMesh(data=8))
        s0 = net.score(x=xs, y=ys)
        SparkDl4jMultiLayer(None, net, master).fit(it, epochs=12)
        assert net.score(x=xs, y=ys) < s0 * 0.5
        acc = (np.argmax(net.output(xs), 1) == np.argmax(ys, 1)).mean()
        assert acc > 0.85, acc

    def test_shared_training_learns(self, rng):
        net, it, xs, ys = _classifier_and_data(rng)
        master = SharedTrainingMaster(threshold=1e-3, mesh=TrainingMesh(data=8))
        s0 = net.score(x=xs, y=ys)
        SparkDl4jMultiLayer(None, net, master).fit(it, epochs=12)
        assert net.score(x=xs, y=ys) < s0 * 0.5
        acc = (np.argmax(net.output(xs), 1) == np.argmax(ys, 1)).mean()
        assert acc > 0.85, acc

    def test_shared_training_matches_dense_direction(self, rng):
        # with a huge threshold nothing transmits on step 1 → params unchanged
        net, it, xs, ys = _classifier_and_data(rng)
        master = SharedTrainingMaster(
            threshold=1e3, mesh=TrainingMesh(data=8),
            accumulator=EncodedGradientsAccumulator(
                threshold_algorithm=FixedThresholdAlgorithm(1e3),
                residual_post_processor=None))
        p0 = np.asarray(net.params[0]["W"]).copy()
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        one = ArrayDataSetIterator(xs[:64], ys[:64], batch=64)
        master.fit(net, one, epochs=1)
        np.testing.assert_allclose(np.asarray(net.params[0]["W"]), p0, atol=1e-7)


def _graph_classifier_and_data(rng, n=256):
    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import (
        ComputationGraph,
        InputType,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(Adam(0.01))
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=16, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                      activation="softmax"), "d1")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4))
        .build()
    )
    net = ComputationGraph(conf).init()
    centers = rng.standard_normal((3, 4)) * 3.0
    ys = rng.integers(0, 3, n)
    xs = (centers[ys] + rng.standard_normal((n, 4))).astype(np.float32)
    yoh = np.eye(3, dtype=np.float32)[ys]
    return net, ArrayDataSetIterator(xs, yoh, batch=64), xs, yoh


@pytest.mark.multichip
class TestTrainingMastersComputationGraph:
    """SparkComputationGraph parity: both masters drive a ComputationGraph."""

    def test_parameter_averaging_graph_then_local_fit(self, rng):
        from deeplearning4j_tpu.data import DataSet
        from deeplearning4j_tpu.parallel import SparkComputationGraph

        net, it, xs, ys = _graph_classifier_and_data(rng)
        master = ParameterAveragingTrainingMaster(
            averaging_frequency=2, mesh=TrainingMesh(data=8))
        s0 = net.score(DataSet(xs, ys))
        SparkComputationGraph(None, net, master).fit(it, epochs=12)
        assert net.score(DataSet(xs, ys)) < s0 * 0.5
        acc = (np.argmax(net.output(xs), 1) == np.argmax(ys, 1)).mean()
        assert acc > 0.85, acc
        # regression: master clears _train_step; local fit must lazily re-jit
        net.fit(xs[:64], ys[:64])

    def test_shared_training_graph_learns(self, rng):
        from deeplearning4j_tpu.data import DataSet
        from deeplearning4j_tpu.parallel import SparkComputationGraph

        net, it, xs, ys = _graph_classifier_and_data(rng)
        master = SharedTrainingMaster(threshold=1e-3, mesh=TrainingMesh(data=8))
        s0 = net.score(DataSet(xs, ys))
        SparkComputationGraph(None, net, master).fit(it, epochs=12)
        assert net.score(DataSet(xs, ys)) < s0 * 0.5
        acc = (np.argmax(net.output(xs), 1) == np.argmax(ys, 1)).mean()
        assert acc > 0.85, acc


def _multi_io_graph_and_data(rng, n=256):
    """2-input/2-output CG: each head is predictable from its own input
    (SharedTrainingWrapper.java wraps arbitrary graphs — VERDICT r2 #3)."""
    from deeplearning4j_tpu.data import MultiDataSet
    from deeplearning4j_tpu.nn import (
        ComputationGraph,
        InputType,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.nn.vertices import MergeVertex

    conf = (
        NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
        .graph_builder()
        .add_inputs("ina", "inb")
        .add_layer("da", DenseLayer(n_in=4, n_out=12, activation="relu"), "ina")
        .add_layer("db", DenseLayer(n_in=3, n_out=12, activation="relu"), "inb")
        .add_vertex("m", MergeVertex(), "da", "db")
        .add_layer("out1", OutputLayer(n_in=24, n_out=2, loss="mcxent",
                                       activation="softmax"), "m")
        .add_layer("out2", OutputLayer(n_in=24, n_out=3, loss="mcxent",
                                       activation="softmax"), "m")
        .set_outputs("out1", "out2")
        .set_input_types(InputType.feed_forward(4), InputType.feed_forward(3))
        .build()
    )
    net = ComputationGraph(conf).init()
    ca = rng.standard_normal((2, 4)) * 3.0
    cb = rng.standard_normal((3, 3)) * 3.0
    la = rng.integers(0, 2, n)
    lb = rng.integers(0, 3, n)
    xa = (ca[la] + rng.standard_normal((n, 4))).astype(np.float32)
    xb = (cb[lb] + rng.standard_normal((n, 3))).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[la]
    y2 = np.eye(3, dtype=np.float32)[lb]
    batches = [
        MultiDataSet(features=[xa[i:i + 64], xb[i:i + 64]],
                     labels=[y1[i:i + 64], y2[i:i + 64]])
        for i in range(0, n, 64)
    ]
    return net, batches, (xa, xb), (y1, y2)


@pytest.mark.multichip
class TestTrainingMastersMultiInOut:
    """Multi-input/multi-output ComputationGraphs under both masters
    (VERDICT r2 next-round #3)."""

    def _assert_learned(self, net, xs, ys):
        o1, o2 = net.output(*xs)
        acc1 = (np.argmax(np.asarray(o1), 1) == np.argmax(ys[0], 1)).mean()
        acc2 = (np.argmax(np.asarray(o2), 1) == np.argmax(ys[1], 1)).mean()
        assert acc1 > 0.85, acc1
        assert acc2 > 0.85, acc2

    def test_shared_training_multi_io(self, rng):
        net, batches, xs, ys = _multi_io_graph_and_data(rng)
        master = SharedTrainingMaster(threshold=1e-3,
                                      mesh=TrainingMesh(data=8))
        s0 = net.score(x=list(xs), y=list(ys))
        master.fit(net, batches, epochs=12)
        assert net.score(x=list(xs), y=list(ys)) < s0 * 0.5
        self._assert_learned(net, xs, ys)

    def test_parameter_averaging_multi_io(self, rng):
        net, batches, xs, ys = _multi_io_graph_and_data(rng)
        master = ParameterAveragingTrainingMaster(
            averaging_frequency=2, mesh=TrainingMesh(data=8))
        s0 = net.score(x=list(xs), y=list(ys))
        master.fit(net, batches, epochs=12)
        assert net.score(x=list(xs), y=list(ys)) < s0 * 0.5
        self._assert_learned(net, xs, ys)


def _masked_recurrent_graph_and_data(rng, n=64, T=12):
    """2-input recurrent CG where input B is noise masked down to t=0; the
    per-input masks must survive the master's shard pipeline."""
    from deeplearning4j_tpu.data import MultiDataSet
    from deeplearning4j_tpu.nn import (
        ComputationGraph,
        InputType,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.nn.vertices import MergeVertex

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .graph_builder()
            .add_inputs("ina", "inb")
            .add_layer("la", LSTM(n_in=4, n_out=10), "ina")
            .add_layer("lb", LSTM(n_in=4, n_out=10), "inb")
            .add_vertex("m", MergeVertex(), "la", "lb")
            .add_layer("out", RnnOutputLayer(n_in=20, n_out=4, loss="mcxent",
                                             activation="softmax"), "m")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(4, T),
                             InputType.recurrent(4, T))
            .build())
    net = ComputationGraph(conf).init()
    ids = rng.integers(0, 4, size=(n, T))
    xa = np.eye(4, dtype=np.float32)[ids]
    sh = np.roll(ids, 1, axis=1)
    sh[:, 0] = ids[:, 0]
    y = np.eye(4, dtype=np.float32)[sh]
    xb = rng.normal(size=(n, T, 4)).astype(np.float32)
    mb = np.zeros((n, T), np.float32)
    mb[:, 0] = 1.0
    mds = MultiDataSet(features=[xa, xb], labels=[y],
                       features_masks=[np.ones((n, T), np.float32), mb])
    return net, mds, xa, xb, y


@pytest.mark.multichip
class TestMastersSequenceMasks:
    """Sequence masks reach the masters' compiled step (review finding:
    the multi-I/O path must not silently drop features_masks)."""

    def test_shared_training_per_input_masks_learns(self, rng):
        net, mds, xa, xb, y = _masked_recurrent_graph_and_data(rng)
        master = SharedTrainingMaster(threshold=1e-4,
                                      mesh=TrainingMesh(data=8))
        master.fit(net, [mds], epochs=300)
        pred = np.argmax(np.asarray(net.output(xa, xb)), axis=-1)
        acc = (pred[:, 1:] == np.argmax(y, -1)[:, 1:]).mean()
        assert acc > 0.85, acc

    def test_parameter_averaging_mask_changes_loss(self, rng):
        """Same data with vs without the mask must give a different first-step
        loss — proves the mask is applied inside the sharded program."""
        from deeplearning4j_tpu.data import MultiDataSet

        net, mds, xa, xb, y = _masked_recurrent_graph_and_data(rng)
        open_mds = MultiDataSet(features=[xa, xb], labels=[y])
        losses = {}
        for name, batch in (("masked", mds), ("open", open_mds)):
            m = ParameterAveragingTrainingMaster(
                averaging_frequency=1, mesh=TrainingMesh(data=8))
            net_i = _masked_recurrent_graph_and_data(rng)[0]
            m.fit(net_i, [batch], epochs=1)
            losses[name] = float(net_i.score_value)
        assert not np.isclose(losses["masked"], losses["open"]), losses


class TestDistributedBootstrap:
    def test_single_process_noop(self):
        distributed.initialize()  # no coordinator, single process: no-op
        assert distributed.process_count() == 1
        assert distributed.is_coordinator()

    def test_global_mesh_shapes(self):
        m = distributed.global_mesh(model=2)
        assert m.model == 2
        assert m.n_devices == len(jax.devices())

    def test_multi_process_bootstrap_and_dp_step(self):
        """The reference tests its cluster path without a cluster (embedded
        MediaDriver / local[N] Spark — SURVEY.md §4); the equivalent here:
        two real OS processes, coordinator on localhost, global 4-device mesh
        (2 virtual CPU devices per process), 20 data-parallel steps with the
        partitioner-emitted cross-process gradient all-reduce. Params must
        come out IDENTICAL on both processes and fit the target."""
        import json
        import os
        import socket
        import subprocess
        import sys

        with socket.socket() as s:  # free localhost port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        coordinator = f"127.0.0.1:{port}"
        worker = os.path.join(os.path.dirname(__file__), "_dist_worker.py")
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        procs = [
            subprocess.Popen(
                [sys.executable, worker, coordinator, "2", str(pid)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env)
            for pid in (0, 1)
        ]
        outs = []
        for p in procs:
            try:
                out, err = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            assert p.returncode == 0, err[-2000:]
            outs.append(json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]))
        assert all(o["n_devices_global"] == 4 for o in outs), outs
        assert outs[0]["w"] == outs[1]["w"], outs  # identical replicas
        assert outs[0]["err"] < 0.5, outs  # learning happened
        # (identity of replicas above is the core assertion; 30
        #  gloo-allreduce steps on one host core cannot fully converge)


@pytest.mark.multichip
class TestCompressionAtScale:
    """VERDICT r2 next-round #6: the threshold/residual chain at a real
    parameter count (25M), where encode cost, bitmap density, and residual
    memory actually bite — not the toy gradient sizes of the unit tests."""

    N_PARAMS = 25_000_000

    def _big_net(self, rng):
        from deeplearning4j_tpu.nn import (
            InputType,
            MultiLayerNetwork,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd

        # 2048*4096 + 4096*4096 + 4096*16 ≈ 25.3M params
        conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.01))
                .list()
                .layer(DenseLayer(n_in=2048, n_out=4096, activation="relu"))
                .layer(DenseLayer(n_in=4096, n_out=4096, activation="relu"))
                .layer(OutputLayer(n_in=4096, n_out=16, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(2048))
                .build())
        net = MultiLayerNetwork(conf).init()
        n = sum(int(np.prod(np.shape(p)))
                for lp in net.params for p in lp.values())
        assert n >= self.N_PARAMS, n
        return net

    def test_encode_decode_conservation_25m(self, rng):
        """Accumulator invariant at 25M elements: quantized + new_residual
        == grad + old_residual to fp32 rounding (error feedback loses
        nothing but low bits — subtracting ±t then re-adding loses up to
        ~2e-10 at this scale)."""
        import jax.numpy as jnp

        acc = EncodedGradientsAccumulator(residual_post_processor=None)
        g = jnp.asarray(rng.standard_normal(self.N_PARAMS).astype(np.float32)
                        * 1e-3)
        res = jnp.zeros_like(g)
        thr = jnp.asarray(1e-3, jnp.float32)
        quant, new_res, _, ratio = acc.encode(
            {"g": g}, {"g": res}, thr, jnp.asarray(0))
        np.testing.assert_allclose(
            np.asarray(quant["g"] + new_res["g"]), np.asarray(g),
            atol=1e-9)
        # sane sparsity at threshold=sigma/… : some but not all transmitted
        assert 0.0 < float(ratio) < 1.0
        # transmitted entries move a multiple of t; untransmitted are intact
        nz = np.asarray(quant["g"]) != 0
        assert np.all(np.abs(np.asarray(quant["g"])[nz]) == np.float32(1e-3))

    # tier-1 runtime guard (ISSUE 11 satellite): ~22s of 25M-param fit
    # steps; the conservation test above pins the 25M threshold chain and
    # the small shared-master tests cover the master seam in tier-1 — the
    # full-suite CI leg still runs this
    @pytest.mark.slow
    def test_shared_training_master_25m_steps(self, rng):
        """3 full SharedTrainingMaster steps at 25M params on the 8-device
        mesh: loss finite AND moving (a frozen loss means the threshold
        chain swallowed every gradient), step time within a collapse-
        detection factor of the dense (uncompressed) DP step."""
        import time

        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.parallel import ParallelWrapper

        xs = rng.standard_normal((32, 2048)).astype(np.float32)
        ys = np.eye(16, dtype=np.float32)[rng.integers(0, 16, 32)]
        it = ArrayDataSetIterator(xs, ys, batch=32)

        net = self._big_net(rng)
        master = SharedTrainingMaster(threshold=1e-4,
                                      mesh=TrainingMesh(data=8))
        master.fit(net, it, epochs=1)  # compile + first step
        s_first = float(net.score_value)
        t0 = time.perf_counter()
        master.fit(net, it, epochs=2)
        shared_dt = (time.perf_counter() - t0) / 2
        assert np.isfinite(net.score_value)
        assert float(net.score_value) != s_first  # gradients DO transmit

        net2 = self._big_net(rng)
        pw = ParallelWrapper(net2, mesh=TrainingMesh(data=8))
        pw.fit(it, epochs=1)
        t0 = time.perf_counter()
        pw.fit(it, epochs=2)
        dense_dt = (time.perf_counter() - t0) / 2
        # Measured on this single-core host: shared ≈ 8.4x dense (13.8 s vs
        # 1.6 s) — the 8 virtual devices each encode a full 25M-element
        # gradient copy + carry an (8, 25M) residual, all on ONE core, so
        # this measures host memory bandwidth, not the ICI design (numbers
        # in BASELINE.md). The bound is a collapse detector (e.g. an
        # accidental O(n^2) or per-element host loop), not a perf target.
        assert shared_dt < dense_dt * 20 + 10.0, (shared_dt, dense_dt)
