"""Profiler, NaN panic, stats storage, crash dump.

Reference test parity: OpProfiler/ProfilerConfig tests and StatsListener →
StatsStorage round-trips (SURVEY.md §5.1/5.5)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.util import (
    CrashReportingUtil,
    FileStatsStorage,
    InMemoryStatsStorage,
    NaNPanicError,
    OpProfiler,
    ProfilerConfig,
    StatsListener,
    StepTimer,
    check_numerics,
    to_csv,
)


class TestOpProfiler:
    def test_records_op_timings(self):
        from deeplearning4j_tpu.ops import registry

        prof = OpProfiler(ProfilerConfig())
        x = jnp.ones((8, 8))
        with prof.profile():
            registry.exec_op("add", x, x)
            registry.exec_op("add", x, x)
            registry.exec_op("matmul", x, x)
        assert prof.invocations["add"] == 2
        assert prof.invocations["matmul"] == 1
        assert prof.total_ns["add"] > 0
        assert "add" in prof.summary()

    def test_hook_removed_after_stop(self):
        from deeplearning4j_tpu.ops import registry

        prof = OpProfiler(ProfilerConfig())
        with prof.profile():
            pass
        before = len(prof.events)
        registry.exec_op("add", jnp.ones(2), jnp.ones(2))
        assert len(prof.events) == before

    def test_chrome_trace_format(self, tmp_path):
        from deeplearning4j_tpu.ops import registry

        prof = OpProfiler(ProfilerConfig())
        with prof.profile():
            registry.exec_op("sum", jnp.ones((4,)))
        p = tmp_path / "trace.json"
        prof.write_chrome_trace(str(p))
        data = json.loads(p.read_text())
        assert data["traceEvents"][0]["ph"] == "X"
        assert data["traceEvents"][0]["name"] == "sum"

    def test_nan_panic(self):
        from deeplearning4j_tpu.ops import registry

        prof = OpProfiler(ProfilerConfig(check_for_nan=True))
        with prof.profile():
            with pytest.raises(NaNPanicError, match="log"):
                registry.exec_op("log", jnp.asarray([-1.0]))  # NaN

    def test_check_numerics(self):
        check_numerics({"w": jnp.ones(3)})
        with pytest.raises(NaNPanicError, match="w"):
            check_numerics({"w": jnp.asarray([1.0, np.nan])})

    def test_check_numerics_reports_nested_keypath(self):
        """ISSUE 4 satellite: the error names the offending LEAF's pytree
        key-path (tree_flatten_with_path), not just the enclosing label."""
        tree = {"layer0": {"W": jnp.ones((2, 2)), "b": jnp.zeros(2)},
                "layer1": [jnp.ones(3),
                           jnp.asarray([np.inf, 1.0, np.nan])]}
        with pytest.raises(NaNPanicError) as exc:
            check_numerics(tree, where="grads")
        msg = str(exc.value)
        assert "grads['layer1'][1]" in msg  # the exact leaf, not 'layer1'
        assert "nan=1" in msg and "inf=1" in msg
        assert "shape=(3,)" in msg
        assert "layer0" not in msg  # healthy leaves are not blamed

    def test_check_numerics_reports_every_bad_leaf(self):
        with pytest.raises(NaNPanicError) as exc:
            check_numerics({"a": jnp.asarray([np.nan]),
                            "z": jnp.asarray([np.inf])})
        assert "['a']" in str(exc.value) and "['z']" in str(exc.value)


class TestSummary:
    """ISSUE 4 satellite: _summary must be NaN-safe on degenerate arrays."""

    def test_empty_array_returns_nan_safe_summary(self):
        from deeplearning4j_tpu.util.stats import _summary

        s = _summary(np.zeros((0, 4), np.float32), bins=10)
        assert np.isnan(s["mean"]) and np.isnan(s["std"])
        assert np.isnan(s["min"]) and np.isnan(s["max"])
        assert s["l2"] == 0.0
        assert "hist" not in s  # no fabricated histogram for no data

    def test_nonfinite_values_do_not_break_histogram(self):
        from deeplearning4j_tpu.util.stats import _summary

        s = _summary(np.asarray([1.0, np.nan, 2.0, np.inf]), bins=4)
        assert sum(s["hist"]) == 2  # only the finite values binned
        assert s["hist_range"] == [1.0, 2.0]

    def test_all_nonfinite_skips_histogram(self):
        from deeplearning4j_tpu.util.stats import _summary

        s = _summary(np.asarray([np.nan, np.inf]), bins=4)
        assert "hist" not in s  # nothing finite to bin, and no crash

    def test_stats_listener_survives_empty_param_leaf(self, rng):
        """The regression that motivated the fix: a 0-sized leaf in the
        param tree must not crash iteration_done."""
        from deeplearning4j_tpu.util.stats import _summary

        flat = {"layer0.W": np.zeros((0,), np.float32)}
        out = {k: _summary(v, bins=8) for k, v in flat.items()}
        assert np.isnan(out["layer0.W"]["mean"])


class TestStats:
    def _train(self, listener, rng):
        from deeplearning4j_tpu.nn import (
            InputType, MultiLayerNetwork, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        net.listeners.append(listener)
        x = rng.standard_normal((16, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        for _ in range(5):
            net._fit_batch(x, y)
        return net

    def test_stats_listener_memory(self, rng):
        storage = InMemoryStatsStorage()
        self._train(StatsListener(storage, frequency=1), rng)
        assert len(storage.records) == 5
        r = storage.records[-1]
        assert "layer0.W" in r["params"]
        assert {"mean", "std", "min", "max", "l2"} <= set(r["params"]["layer0.W"])
        assert "updates" in r
        assert len(storage.scores()) == 5

    def test_file_storage_roundtrip_and_csv(self, rng, tmp_path):
        p = tmp_path / "stats.jsonl"
        storage = FileStatsStorage(str(p))
        self._train(StatsListener(storage, frequency=2,
                                  collect_histograms=False), rng)
        reloaded = FileStatsStorage(str(p))
        assert len(reloaded.records) == len(storage.records) > 0
        csv = tmp_path / "curves.csv"
        to_csv(reloaded, str(csv))
        assert csv.read_text().startswith("session,iteration")

    def test_step_timer_trace(self, rng, tmp_path):
        timer = StepTimer()
        self._train(timer, rng)
        p = tmp_path / "steps.json"
        timer.write_chrome_trace(str(p))
        ev = json.loads(p.read_text())["traceEvents"]
        assert len(ev) == 4  # N-1 intervals
        assert all(e["dur"] > 0 for e in ev)

    def test_profiler_and_telemetry_traces_share_timebase(self, tmp_path):
        """ISSUE 5 satellite: OpProfiler.write_chrome_trace and
        Telemetry.write_chrome_trace subtract the SAME wall-clock origin
        (telemetry.trace_epoch_ns), so the two files load into one Perfetto
        view on one timeline — an op profiled INSIDE a telemetry span must
        land within that span's exported [ts, ts+dur] interval."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.ops import registry
        from deeplearning4j_tpu.util import telemetry as tm
        from deeplearning4j_tpu.util.profiler import (OpProfiler,
                                                      ProfilerConfig)

        tele = tm.get_telemetry()
        tele.reset()
        was = tele.enabled
        tele.enabled = True
        prof = OpProfiler(ProfilerConfig())
        try:
            with prof.profile():
                with tm.span("outer.window"):
                    registry.exec_op("add", jnp.ones(128), jnp.ones(128))
        finally:
            tele.enabled = was
        p1 = tmp_path / "ops.json"
        p2 = tmp_path / "spans.json"
        prof.write_chrome_trace(str(p1))
        tele.write_chrome_trace(str(p2))
        tele.reset()
        op = json.loads(p1.read_text())["traceEvents"][0]
        spans = [e for e in json.loads(p2.read_text())["traceEvents"]
                 if e.get("name") == "outer.window"]
        assert spans, "telemetry span missing from its own trace"
        span = spans[0]
        # same timebase: the op interval nests inside the span interval
        # (small slack for the ns->µs rounding at export)
        assert span["ts"] - 1 <= op["ts"]
        assert op["ts"] + op["dur"] <= span["ts"] + span["dur"] + 1

    def test_crash_dump(self, rng, tmp_path):
        net = self._train(StepTimer(), rng)
        p = tmp_path / "crash.json"
        try:
            raise MemoryError("boom")
        except MemoryError as e:
            CrashReportingUtil.write_crash_dump(net, str(p), e)
        info = json.loads(p.read_text())
        assert info["exception"] == "MemoryError('boom')"
        assert info["param_bytes"]["layer0.W"] > 0
        assert info["config"] == ["DenseLayer", "OutputLayer"]

    def test_crash_dump_config_memory_telemetry(self, rng, tmp_path):
        """ISSUE 4 satellite: a simulated training failure's dump carries
        the full config JSON, memory stats, and the last-N telemetry
        counters/events that were in flight when it died."""
        from deeplearning4j_tpu.util import telemetry as tm

        tele = tm.get_telemetry()
        tele.reset()
        was = tele.enabled
        tele.enabled = True
        try:
            net = self._train(StepTimer(), rng)
            p = tmp_path / "crash2.json"
            try:  # simulate a mid-fit failure
                net._fit_batch(np.full((16, 4), np.nan, np.float32),
                               np.eye(2, dtype=np.float32)[[0] * 16])
                raise FloatingPointError("loss went non-finite")
            except FloatingPointError as e:
                CrashReportingUtil.write_crash_dump(net, str(p), e)
            info = json.loads(p.read_text())
            # config JSON reproduces the topology
            cfg = info["config_json"]
            assert cfg and "layers" in json.dumps(cfg)
            # memory stats: host view of param buffers always present;
            # device stats when the backend reports them (None on CPU)
            assert info["param_bytes"]["layer0.W"] > 0
            assert "device_memory_stats" in info and "hbm" in info
            # telemetry: the training counters + the last-N trace events
            tl = info["telemetry"]
            assert tl["counters"]["train.steps_total{model=mln}"] >= 6
            assert tl["histograms"]["train.step_seconds{model=mln}"][
                "count"] >= 1
            assert tl["recent_events"], "last-N trace events missing"
            assert any(e["name"] == "mln.train_step"
                       for e in tl["recent_events"])
        finally:
            tele.enabled = was
            tele.reset()
