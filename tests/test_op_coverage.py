"""Op-coverage gate: every registered op must execute on canonical inputs.

Reference test parity: the nd4j OpValidation framework's COVERAGE ACCOUNTING
(SURVEY.md §4: "fails CI if an op has no test"). Here the gate is executable:
each registered op runs forward on category-appropriate sample inputs (with a
per-op override table for special signatures) and must return finite,
non-error output. Ops with deeper numeric/gradient coverage elsewhere in the
suite still run here — this is the breadth floor, not the depth ceiling.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import registry

KEY = jax.random.PRNGKey(0)
X = jnp.linspace(0.1, 0.9, 24).reshape(4, 6)          # generic 2-D, positive
XN = jnp.linspace(-0.9, 0.9, 24).reshape(4, 6)        # generic signed
IMG = jnp.linspace(0.0, 1.0, 96).reshape(1, 4, 4, 6)  # NHWC
SQ = jnp.asarray([[2.0, 0.4], [0.4, 1.0]])            # SPD 2x2
IDX = jnp.asarray([0, 1, 1, 0])

# ops whose first argument is not an array (or otherwise special)
OVERRIDES = {
    "ssim": lambda f: f(jnp.ones((1, 16, 16, 3)), jnp.ones((1, 16, 16, 3)) * 0.5,
                        filter_size=5),
    "kron": lambda f: f(XN[:2, :2], XN[:3, :3]),
    "matrix_power": lambda f: f(SQ, 3),
    "pinv": lambda f: f(SQ),
    "slogdet": lambda f: f(SQ),
    "matrix_rank": lambda f: f(SQ),
    "expm": lambda f: f(SQ * 0.1),
    "sqrtm": lambda f: f(SQ),
    "adjoint": lambda f: f(SQ),
    "logdet": lambda f: f(SQ),
    "cond_number": lambda f: f(SQ),
    "vander": lambda f: f(jnp.asarray([1.0, 2.0, 3.0])),
    "normalize_moments": lambda f: f(
        jnp.float32(8.0), jnp.asarray([4.0, 8.0]), jnp.asarray([10.0, 40.0])),
    "log_poisson_loss": lambda f: f(XN, jnp.abs(XN)),
    "toeplitz": lambda f: f(jnp.asarray([1.0, 2.0, 3.0])),
    "lstm_block": lambda f: f(
        3, jnp.ones((4, 2, 3)), jnp.zeros((2, 5)), jnp.zeros((2, 5)),
        jnp.ones((8, 20)) * 0.1, jnp.zeros(5), jnp.zeros(5), jnp.zeros(5),
        jnp.zeros(20)),
    "lstm_block_cell": lambda f: f(
        jnp.ones((2, 3)), jnp.zeros((2, 5)), jnp.zeros((2, 5)),
        jnp.ones((8, 20)) * 0.1, jnp.zeros(5), jnp.zeros(5), jnp.zeros(5),
        jnp.zeros(20)),
    "mergeadd": lambda f: f(XN, XN, XN),
    "mergeavg": lambda f: f(XN, XN, XN),
    "mergemax": lambda f: f(XN, XN, XN),
    # TF-grad-kernel ops (round 4): (dy, y/x) pairs and conv/pool backprops
    "relu_grad": lambda f: f(XN, XN),
    "relu6_grad": lambda f: f(XN, XN),
    "tanh_grad": lambda f: f(jnp.tanh(XN), XN),
    "sigmoid_grad": lambda f: f(jax.nn.sigmoid(XN), XN),
    "bias_add_grad": lambda f: f(IMG),
    "conv2d_backprop_input": lambda f: f(
        jnp.ones((2, 2, 6, 3)), jnp.ones((1, 4, 4, 3)),
        input_sizes=(1, 4, 4, 6)),
    "conv2d_backprop_filter": lambda f: f(
        IMG, jnp.ones((1, 4, 4, 3)), filter_sizes=(2, 2, 6, 3)),
    "maxpool2d_grad": lambda f: f(IMG, jnp.ones((1, 2, 2, 6))),
    "avgpool2d_grad": lambda f: f(IMG, jnp.ones((1, 2, 2, 6))),
    "fused_batch_norm_grad": lambda f: f(
        IMG, IMG, jnp.ones(6), jnp.zeros(6), jnp.ones(6)),
    "strided_slice_grad": lambda f: f(
        XN[:2], shape=(4, 6), spec=(("s", 0, 2, 1), ("s", None, None, 1))),
    "softmax_cross_entropy_with_logits_grad": lambda f: f(
        XN, jax.nn.one_hot(IDX, 6)),
    "alpha_dropout": lambda f: f(XN, KEY, 0.3, training=True),
    "dropout": lambda f: f(XN, KEY, 0.3, training=True),
    "dropout_inverted": lambda f: f(XN, KEY, 0.3, training=True),
    "axpy": lambda f: f(XN, XN, alpha=0.5),
    "batched_gemm": lambda f: f(jnp.ones((2, 3, 4)), jnp.ones((2, 4, 5))),
    "batch_dot": lambda f: f(jnp.ones((2, 3, 4)), jnp.ones((2, 3, 4))),
    "im2col": lambda f: f(IMG, (2, 2)),
    "ctc_loss": lambda f: f(
        jax.nn.log_softmax(jnp.zeros((2, 8, 5))),
        jnp.asarray([[1, 2, 0], [3, 0, 0]]),
        jnp.asarray([8, 8]), jnp.asarray([2, 1])),
    "in_top_k": lambda f: f(XN, IDX, 2),
    "top_k": lambda f: f(XN, 2),
    "lstsq": lambda f: f(SQ, jnp.ones((2, 1))),
    "meshgrid": lambda f: f(jnp.arange(3.0), jnp.arange(2.0)),
    "mmul_vector": lambda f: f(X, jnp.ones((6,))),
    "prelu": lambda f: f(XN, jnp.full((6,), 0.1)),
    "random_categorical": lambda f: f(KEY, jnp.zeros((2, 5))),
    "random_choice": lambda f: f(KEY, jnp.arange(10.0), (4,)),
    "random_split_key": lambda f: f(KEY),
    "scalar_set": lambda f: f(XN, 2.0),
    "searchsorted": lambda f: f(jnp.arange(10.0), jnp.asarray([2.5, 7.1])),
    "space_to_depth": lambda f: f(IMG, 2),
    "batch_to_space": lambda f: f(jnp.ones((4, 2, 2, 1)), (2, 2),
                                  [[0, 0], [0, 0]]),
    "acosh": lambda f: f(X + 1.0),
    "cast": lambda f: f(XN, jnp.int32),
    "matmul": lambda f: f(XN, XN.T),
    "mmul": lambda f: f(XN, XN.T),
    "moments": lambda f: f(XN, (0,)),
    "l2_loss": lambda f: f(XN),
    "random_binomial": lambda f: f(KEY, (3, 4), 10, 0.5),
    "random_gamma": lambda f: f(KEY, (3, 4), 2.0),
    "random_poisson": lambda f: f(KEY, (3, 4), 3.0),
    "random_shuffle": lambda f: f(KEY, XN),
    "segment_sum": lambda f: f(XN, IDX, 2),
    "segment_mean": lambda f: f(XN, IDX, 2),
    "segment_max": lambda f: f(XN, IDX, 2),
    "segment_min": lambda f: f(XN, IDX, 2),
    "segment_prod": lambda f: f(XN, IDX, 2),
    "unique_with_counts": lambda f: f(jnp.asarray([1, 2, 2, 3])),
    "invert_permutation": lambda f: f(jnp.asarray([2, 0, 1, 3])),
    "listdiff": lambda f: f(jnp.asarray([1, 2, 3, 4]), jnp.asarray([2, 4])),
    "nth_element": lambda f: f(XN, 2),
    "batch_gather": lambda f: f(XN, jnp.asarray([[0, 2], [1, 3], [0, 0], [5, 1]])),
    "tensor_scatter_update": lambda f: f(XN, jnp.asarray([[0], [2]]),
                                         XN[:2]),
    "sparse_to_dense": lambda f: f(jnp.asarray([[0, 1], [2, 3]]), (4, 6),
                                   jnp.asarray([1.0, 2.0])),
    "logspace": lambda f: f(0.0, 2.0, 5),
    "divide_no_nan": lambda f: f(XN, X.at[0, 0].set(0.0)),
    "toggle_bits": lambda f: f(jnp.asarray([1, 2, 3], jnp.int32)),
    "cyclic_shift_bits": lambda f: f(jnp.asarray([1, 2], jnp.int32), 3),
    "cumlogsumexp": lambda f: f(XN),
    "clip_by_global_norm": lambda f: f([XN, X], 1.0),
    "clipbyavgnorm": lambda f: f(XN, 0.01),
    "einsum_apply": lambda f: f(XN, X, equation="ij,ij->i"),
    "entropy": lambda f: f(X),
    "shannon_entropy": lambda f: f(X),
    "log_entropy": lambda f: f(X),
    "weighted_cross_entropy_with_logits": lambda f: f(
        (XN > 0).astype(jnp.float32), XN, 2.0),
    "col2im": lambda f: f(
        registry.get_op("im2col").fn(IMG, (2, 2)), IMG.shape, (2, 2)),
    "depth_to_space": lambda f: f(jnp.ones((1, 4, 4, 8)), 2),
    "dynamic_stitch": lambda f: f([jnp.asarray([0, 2]), jnp.asarray([1, 3])],
                                  [jnp.ones((2, 3)), jnp.zeros((2, 3))]),
    "dynamic_partition": lambda f: f(XN, jnp.asarray([0, 1, 0, 1]), 2),
    "gather_nd": lambda f: f(XN, jnp.asarray([[0, 1], [2, 3]])),
    "tensormmul": lambda f: f(XN, XN, (1,), (1,)),
    "vdot": lambda f: f(jnp.ones(6), jnp.ones(6)),
    "outer": lambda f: f(jnp.ones(3), jnp.ones(4)),
    "triangular_solve": lambda f: f(SQ, jnp.ones((2, 1))),
    "solve": lambda f: f(SQ, jnp.ones((2, 1))),
    "cholesky": lambda f: f(SQ),
    "matrix_inverse": lambda f: f(SQ),
    "matrix_determinant": lambda f: f(SQ),
    "log_matrix_determinant": lambda f: f(SQ),
    "svd": lambda f: f(SQ),
    "qr": lambda f: f(SQ),
    "lu": lambda f: f(SQ),
    "eig": lambda f: f(SQ),
    "eigh": lambda f: f(SQ),
    "trace": lambda f: f(SQ),
    "matrix_diag": lambda f: f(jnp.ones(3)),
    "matrix_diag_part": lambda f: f(SQ),
    "clipbynorm": lambda f: f(XN, 1.0),
    "clipbyvalue": lambda f: f(XN, -0.5, 0.5),
    "conv1d": lambda f: f(jnp.ones((1, 8, 3)), jnp.ones((3, 3, 4))),
    "conv3d": lambda f: f(jnp.ones((1, 4, 4, 4, 2)), jnp.ones((2, 2, 2, 2, 3))),
    "avgpool3d": lambda f: f(jnp.ones((1, 4, 4, 4, 2))),
    "maxpool3d": lambda f: f(jnp.ones((1, 4, 4, 4, 2))),
    "pnormpool2d": lambda f: f(IMG),
    "unique": lambda f: f(jnp.asarray([1.0, 2.0, 1.0]), size=3),
    "one_hot": lambda f: f(IDX, 3),
    "confusion_matrix": lambda f: f(IDX, IDX),
    "eye": lambda f: f(3),
    "linspace": lambda f: f(0.0, 1.0, 5),
    "arange": lambda f: f(5),
    "zeros": lambda f: f((2, 3)),
    "ones": lambda f: f((2, 3)),
    "full": lambda f: f((2, 3), 7.0),
    "tri": lambda f: f(3),
    "repeat": lambda f: f(XN, 2),
    "tile": lambda f: f(XN, (2, 1)),
    "reshape": lambda f: f(XN, (6, 4)),
    "permute": lambda f: f(XN, (1, 0)),
    "broadcast_to": lambda f: f(jnp.ones((1, 6)), (4, 6)),
    "expand_dims": lambda f: f(XN, 0),
    "stack": lambda f: f([XN, XN]),
    "concat": lambda f: f([XN, XN]),
    "concat_n": lambda f: f(XN, XN),
    "stack_n": lambda f: f(XN, XN),
    "unstack": lambda f: f(XN),
    "split": lambda f: f(XN, 2),
    "split_v": lambda f: f(XN, [2, 2]),
    "slice": lambda f: f(XN, [0, 0], [2, 2]),
    "strided_slice": lambda f: f(XN, [0, 0], [2, 2]),
    "getitem": lambda f: f(XN, spec=(("i", 0),)),
    "pad": lambda f: f(XN, ((1, 1), (0, 0))),
    "take": lambda f: f(XN, IDX),
    "take_along_axis": lambda f: f(XN, jnp.zeros((4, 1), jnp.int32), 1),
    "gather": lambda f: f(XN, IDX),
    "scatter_update": lambda f: f(XN, IDX[:2], XN[:2]),
    "scatter_add": lambda f: f(XN, IDX[:2], XN[:2]),
    "scatter_sub": lambda f: f(XN, IDX[:2], XN[:2]),
    "scatter_mul": lambda f: f(XN, IDX[:2], XN[:2]),
    "scatter_div": lambda f: f(XN, IDX[:2], XN[:2] + 1.0),
    "scatter_max": lambda f: f(XN, IDX[:2], XN[:2]),
    "scatter_min": lambda f: f(XN, IDX[:2], XN[:2]),
    "scatter_nd": lambda f: f(jnp.asarray([[0], [2]]), jnp.ones((2, 6)), (4, 6)),
    "embedding_lookup": lambda f: f(XN, IDX),
    "where": lambda f: f(XN > 0, XN, -XN),
    "cumsum": lambda f: f(XN, 0),
    "cumprod": lambda f: f(XN, 0),
    "rdiv": lambda f: f(XN + 2.0, XN + 3.0),
    "rsub": lambda f: f(XN, XN),
    "l2_normalize": lambda f: f(XN),
    "rmsnorm": lambda f: f(XN),
    "roll": lambda f: f(XN, 1),
    "flip": lambda f: f(XN),
    "rot90": lambda f: f(XN),
    "swapaxes": lambda f: f(XN, 0, 1),
    "moveaxis": lambda f: f(XN, 0, 1),
    "squeeze": lambda f: f(jnp.ones((1, 4))),
    "atan2": lambda f: f(XN, X),
    "pow": lambda f: f(X, 2.0),
    "fmod": lambda f: f(XN, 2.0),
    "mod": lambda f: f(XN, 2.0),
    "floordiv": lambda f: f(XN, 2.0),
    "truncatediv": lambda f: f(XN, 2.0),
    "copysign": lambda f: f(XN, -jnp.ones_like(XN)),
    "hypot": lambda f: f(XN, X),
    "shift_left": lambda f: f(jnp.asarray([1, 2]), 1),
    "shift_right": lambda f: f(jnp.asarray([4, 8]), 1),
    "and": lambda f: f(XN > 0, X > 0.5),
    "or": lambda f: f(XN > 0, X > 0.5),
    "xor": lambda f: f(XN > 0, X > 0.5),
    "not": lambda f: f(XN > 0),
    "cross": lambda f: f(jnp.ones((2, 3)), jnp.ones((2, 3))),
    "diag": lambda f: f(jnp.ones(3)),
    "step": lambda f: f(XN),
    "zeroslike": lambda f: f(XN),
    "oneslike": lambda f: f(XN),
    "triu": lambda f: f(SQ),
    "tril": lambda f: f(SQ),
    "onehot": lambda f: f(IDX, 3),
    "argsort": lambda f: f(XN),
    "sort": lambda f: f(XN),
    "thresholdrelu": lambda f: f(XN),
    "leakyrelu": lambda f: f(XN),
    "threshold_encode": lambda f: f(XN, 0.1),
    "threshold_decode": lambda f: f(XN),
    "threshold_encode_exact": lambda f: f(XN, 0.1),
    "onebit_encode": lambda f: f(XN),
    "pow2_floor": lambda f: f(0.3),
    # weight-only int8 serving pair (ISSUE 15, serving/quantize.py)
    "quantize_per_channel": lambda f: f(XN, jnp.full((1, 6), 0.01)),
    "dequantize_per_channel": lambda f: f(
        jnp.asarray(XN * 100, jnp.int8), jnp.full((1, 6), 0.01)),
    "bitmap_encode": lambda f: f(XN, 0.1),
    "bitmap_decode": lambda f: None,  # needs encode output; covered in test_distributed
    "lstm_layer": lambda f: f(jnp.ones((3, 2, 4)), jnp.ones((1, 8, 4)) * 0.1,
                              jnp.ones((1, 8, 2)) * 0.1, hidden_size=2),
    "gru_layer": lambda f: f(jnp.ones((3, 2, 4)), jnp.ones((1, 6, 4)) * 0.1,
                             jnp.ones((1, 6, 2)) * 0.1, hidden_size=2),
    "rnn_layer": lambda f: f(jnp.ones((3, 2, 4)), jnp.ones((1, 2, 4)) * 0.1,
                             jnp.ones((1, 2, 2)) * 0.1, hidden_size=2),
    "lstm_cell": lambda f: f(jnp.ones((2, 4)), jnp.zeros((2, 3)),
                             jnp.zeros((2, 3)), jnp.ones((12, 4)) * 0.1,
                             jnp.ones((12, 3)) * 0.1),
    "gru_cell": lambda f: f(jnp.ones((2, 4)), jnp.zeros((2, 3)),
                            jnp.ones((9, 4)) * 0.1, jnp.ones((9, 3)) * 0.1),
    "sequence_mask": lambda f: f(jnp.asarray([1, 3, 2]), 4),
    "sru_cell": lambda f: f(jnp.ones((2, 4)), jnp.zeros((2, 4)),
                            jnp.ones((12, 4)) * 0.1, jnp.zeros((8,))),
    "sru": lambda f: f(jnp.ones((2, 3, 4)), jnp.ones((12, 4)) * 0.1,
                       jnp.zeros((8,))),
    "conv_lstm_2d": lambda f: f(jnp.ones((1, 2, 4, 4, 3)),
                                jnp.ones((3, 3, 3, 8)) * 0.1,
                                jnp.ones((3, 3, 2, 8)) * 0.1),
    "space_to_batch": lambda f: f(jnp.ones((1, 4, 4, 1)), (2, 2),
                                  [[0, 0], [0, 0]]),
    # image ops
    "image_resize": lambda f: f(IMG, (2, 2)),
    "resize_bilinear": lambda f: f(IMG, (2, 2)),
    "resize_nearest": lambda f: f(IMG, (2, 2)),
    "resize_bicubic": lambda f: f(IMG, (8, 8)),
    "crop_and_resize": lambda f: f(IMG, jnp.asarray([[0.0, 0.0, 1.0, 1.0]]),
                                   jnp.asarray([0]), (2, 2)),
    "extract_image_patches": lambda f: f(IMG, (2, 2)),
    "non_max_suppression": lambda f: f(
        jnp.asarray([[0, 0, 1, 1], [0.5, 0.5, 1, 1]]),
        jnp.asarray([0.9, 0.8]), 2),
    "adjust_brightness": lambda f: f(IMG, 0.1),
    "adjust_contrast": lambda f: f(IMG, 1.5),
    "adjust_saturation": lambda f: f(IMG[..., :3] / 2 + 0.2, 1.2),
    "adjust_hue": lambda f: f(IMG[..., :3] / 2 + 0.2, 0.1),
    "rgb_to_hsv": lambda f: f(IMG[..., :3] / 2 + 0.2),
    "hsv_to_rgb": lambda f: f(IMG[..., :3] / 2 + 0.2),
    "rgb_to_grayscale": lambda f: f(IMG[..., :3] / 2),
    "rgb_to_yuv": lambda f: f(IMG[..., :3] / 2),
    "yuv_to_rgb": lambda f: f(IMG[..., :3] / 2),
    "flip_left_right": lambda f: f(IMG),
    "flip_up_down": lambda f: f(IMG),
    "random_crop": lambda f: f(KEY, IMG, (2, 2)),
    # order stats / histograms
    "histogram": lambda f: f(XN, 4),
    "histogram_fixed_width": lambda f: f(XN, (-1.0, 1.0), 4),
    "bincount": lambda f: f(jnp.asarray([0, 1, 1, 2]), minlength=3),
    "percentile": lambda f: f(XN, 50.0),
    "quantile": lambda f: f(XN, 0.5),
    # tensorlist (TF2 loop accumulators)
    "tensorlist_reserve": lambda f: f(4),
    "tensorlist_from_tensor": lambda f: f(XN),
    "tensorlist_get_item": lambda f: f(XN, 1),
    "tensorlist_set_item": lambda f: f(jnp.zeros((4, 0)), 1, XN[0]),
    "tensorlist_stack": lambda f: f(XN),
    "tensorlist_length": lambda f: f(XN),
    "reverse_sequence": lambda f: f(XN, jnp.asarray([2, 4, 6, 1])),
    "matrix_band_part": lambda f: f(SQ, 0, 0),
    # special functions
    "igamma": lambda f: f(X + 0.5, X + 0.5),
    "igammac": lambda f: f(X + 0.5, X + 0.5),
    "polygamma": lambda f: f(jnp.ones_like(X), X + 0.5),
    "zeta": lambda f: f(X + 1.5, X + 0.5),
    "betainc": lambda f: f(X + 0.5, X + 0.5, X * 0.5 + 0.2),
    "logit": lambda f: f(X * 0.5 + 0.2),
    # round-5 tail: updater op family (gradient + state tensors)
    "apply_sgd": lambda f: f(XN, XN * 0.1),
    "nesterovs_updater": lambda f: f(XN, jnp.zeros_like(XN)),
    "ada_grad_updater": lambda f: f(XN, jnp.zeros_like(XN)),
    "rms_prop_updater": lambda f: f(XN, jnp.zeros_like(XN)),
    "ada_delta_updater": lambda f: f(XN, jnp.zeros_like(XN),
                                     jnp.zeros_like(XN)),
    "adam_updater": lambda f: f(XN, jnp.zeros_like(XN), jnp.zeros_like(XN)),
    "ada_max_updater": lambda f: f(XN, jnp.zeros_like(XN),
                                   jnp.zeros_like(XN)),
    "ams_grad_updater": lambda f: f(XN, jnp.zeros_like(XN),
                                    jnp.zeros_like(XN), jnp.zeros_like(XN)),
    "nadam_updater": lambda f: f(XN, jnp.zeros_like(XN), jnp.zeros_like(XN)),
    # round-5 tail: NLP / manifold helper ops
    "skipgram": lambda f: f(jnp.ones((5, 4)) * 0.1, jnp.ones((5, 4)) * 0.1,
                            2, jnp.asarray([1, 3]), jnp.asarray([1.0, 0.0])),
    "cbow": lambda f: f(jnp.ones((5, 4)) * 0.1, jnp.ones((5, 4)) * 0.1,
                        jnp.asarray([0, 4]), jnp.asarray([1, 3]),
                        jnp.asarray([1.0, 0.0])),
    "barnes_symmetrized": lambda f: f(jnp.asarray([0, 1]),
                                      jnp.asarray([1, 2]),
                                      jnp.asarray([0.5, 0.25])),
    "barnes_edge_forces": lambda f: f(jnp.asarray([0, 1]),
                                      jnp.asarray([1, 2]),
                                      jnp.asarray([0.5, 0.25]),
                                      jnp.ones((3, 2))),
    "barnes_gains": lambda f: f(jnp.ones((3, 2)), XN[:3, :2], XN[:3, :2]),
    "cell_contains": lambda f: f(jnp.zeros(2), jnp.ones(2),
                                 jnp.asarray([0.5, -0.5])),
    "knn_mindistance": lambda f: f(jnp.zeros(3), -jnp.ones(3), jnp.ones(3)),
    # round-5 tail: conv/pool/decoder
    "dilation2d": lambda f: f(IMG, jnp.zeros((2, 2, 6))),
    "erosion2d": lambda f: f(IMG, jnp.zeros((2, 2, 6))),
    "max_pool_with_argmax": lambda f: f(IMG),
    "deconv3d": lambda f: f(jnp.ones((1, 3, 3, 3, 2)),
                            jnp.ones((2, 2, 2, 2, 4)) * 0.1),
    "upsampling3d": lambda f: f(jnp.ones((1, 2, 2, 2, 3))),
    "relu_layer": lambda f: f(XN, jnp.ones((6, 3)) * 0.1, jnp.zeros(3)),
    "ctc_beam_search_decoder": lambda f: f(
        jax.nn.log_softmax(jnp.zeros((1, 5, 4))), beam_width=4),
    # round-5 tail: static/dynamic RNN + sru_bi
    "static_rnn": lambda f: f(jnp.ones((3, 2, 4)), jnp.ones((4, 3)) * 0.1,
                              jnp.ones((3, 3)) * 0.1),
    "dynamic_rnn": lambda f: f(jnp.ones((3, 2, 4)), jnp.ones((4, 3)) * 0.1,
                               jnp.ones((3, 3)) * 0.1),
    "static_bidirectional_rnn": lambda f: f(
        jnp.ones((3, 2, 4)), jnp.ones((4, 3)) * 0.1, jnp.ones((3, 3)) * 0.1,
        jnp.zeros(3), jnp.ones((4, 3)) * 0.1, jnp.ones((3, 3)) * 0.1,
        jnp.zeros(3)),
    "dynamic_bidirectional_rnn": lambda f: f(
        jnp.ones((3, 2, 4)), jnp.ones((4, 3)) * 0.1, jnp.ones((3, 3)) * 0.1,
        jnp.zeros(3), jnp.ones((4, 3)) * 0.1, jnp.ones((3, 3)) * 0.1,
        jnp.zeros(3)),
    "sru_bi": lambda f: f(jnp.ones((3, 2, 8)), jnp.ones((2, 12, 4)) * 0.1,
                          jnp.zeros((2, 8))),
    # round-5 tail: scatter_nd variants / shape / bit ops
    "scatter_nd_add": lambda f: f(XN, jnp.asarray([[0], [2]]),
                                  jnp.ones((2, 6))),
    "scatter_nd_sub": lambda f: f(XN, jnp.asarray([[0], [2]]),
                                  jnp.ones((2, 6))),
    "scatter_nd_update": lambda f: f(XN, jnp.asarray([[0], [2]]),
                                     jnp.ones((2, 6))),
    "bitcast": lambda f: f(jnp.asarray([1.0, 2.0], jnp.float32), jnp.int32),
    "broadcast_dynamic_shape": lambda f: f(jnp.asarray([2, 1, 3]),
                                           jnp.asarray([2, 4, 1])),
    "cyclic_rshift_bits": lambda f: f(jnp.asarray([1, 2], jnp.int32), 3),
    "bits_hamming_distance": lambda f: f(jnp.asarray([1, 2], jnp.int32),
                                         jnp.asarray([3, 2], jnp.int32)),
    "fake_quant_with_min_max_vars_per_channel": lambda f: f(
        XN, -jnp.ones(6), jnp.ones(6)),
    "compare_and_bitpack": lambda f: f(XN.reshape(3, 8), 0.0),
    # round-5: signal / sampler / loss ops backing the ONNX rule expansion
    "mel_weight_matrix": lambda f: f(4, 16, 8192, 0.0, 4096.0),
    "hann_window": lambda f: f(8),
    "hamming_window": lambda f: f(8),
    "blackman_window": lambda f: f(8),
    "stft": lambda f: f(jnp.ones((1, 32)), frame_length=8, frame_step=4),
    "complex_pack": lambda f: f(jnp.ones((3, 2))),
    "grid_sample": lambda f: f(jnp.ones((1, 2, 4, 4)),
                               jnp.zeros((1, 2, 2, 2))),
    "roi_align": lambda f: f(jnp.ones((1, 2, 8, 8)),
                             jnp.asarray([[0.0, 0.0, 4.0, 4.0]]),
                             jnp.asarray([0]), output_size=(2, 2)),
    "put_along_axis": lambda f: f(XN, jnp.zeros((1, 6), jnp.int32),
                                  jnp.ones((1, 6))),
    "nll_loss": lambda f: f(jax.nn.log_softmax(XN), IDX[:4] % 6),
    "max_unpool2d": lambda f: f(jnp.ones((1, 1, 2, 2)),
                                jnp.asarray([[[[0, 3], [8, 11]]]]),
                                (1, 1, 4, 4)),
    # round-5 tail: linalg
    "lup": lambda f: f(SQ),
    "matrix_set_diag": lambda f: f(SQ, jnp.asarray([5.0, 6.0])),
    "solve_ls": lambda f: f(SQ, jnp.ones((2, 1))),
    "sufficient_statistics": lambda f: f(XN, (0,)),
}

# EXACT category match only ("reduce3".startswith("reduce") must not route
# two-array ops to the unary reduce builder)
CAT_BUILDERS = {
    "random": lambda f: f(KEY, (3, 4)),
    "scalar": lambda f: f(XN, 2.0),
    "pairwise": lambda f: f(XN, X),
    "broadcast": lambda f: f(XN, X),
    "indexreduce": lambda f: f(XN),
    "summarystats": lambda f: f(XN),
    "reduce": lambda f: f(XN),
    "reduce_bool": lambda f: f(XN > 0),
    "reduce3": lambda f: f(XN, X),
    "distance": lambda f: f(XN, X),
    "loss": lambda f: f(jax.nn.softmax(XN), jax.nn.softmax(X)),
    "nn_misc": lambda f: f(jnp.ones((2, 3, 4)), jnp.ones((2, 5, 4))),
    "pairwise_bool": lambda f: f(XN, X),
}

SKIP = {
    # composite/attention/conv ops with dedicated deep tests elsewhere
    "conv2d", "deconv2d", "depthwise_conv2d", "separable_conv2d",
    "dot_product_attention", "flash_attention",
    "multi_head_dot_product_attention", "multihead_attention",
    "batchnorm", "batchnorm_train",
    "layernorm", "lrn", "maxpool2d", "avgpool2d", "upsampling2d",
    "global_avg_pool", "global_max_pool", "xw_plus_b", "bias_add",
    "softmax_cross_entropy", "sigmoid_cross_entropy",
    "sparse_softmax_cross_entropy", "softmax_derivative",
    "sigmoid_derivative", "tanh_derivative", "einsum", "bitmap_decode",
    "ctc_loss",
}


def _sample_call(name):
    od = registry.get_op(name)
    if name in OVERRIDES:
        return OVERRIDES[name](od.fn)
    if od.category in CAT_BUILDERS:
        return CAT_BUILDERS[od.category](od.fn)
    # default: unary array op
    return od.fn(X)


@pytest.mark.parametrize("name", sorted(registry._REGISTRY.keys()))
def test_every_registered_op_executes(name):
    if name in SKIP or registry.get_op(name).category == "custom":
        pytest.skip("covered by dedicated tests")
    out = _sample_call(name)
    for leaf in jax.tree_util.tree_leaves(out):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            assert np.isfinite(arr).all() or name in ("threshold_encode",), name


def test_coverage_is_total():
    """The gate itself: no registered op may be silently unhandled — every op
    is either exercised above or explicitly listed in SKIP (with dedicated
    coverage elsewhere)."""
    missing = []
    for name in registry._REGISTRY:
        od = registry.get_op(name)
        if name in SKIP or od.category == "custom":
            continue
        if name in OVERRIDES:
            continue
        if od.category in CAT_BUILDERS:
            continue
        # will use the unary default: require a 1-array-arg signature
        import inspect

        params = list(inspect.signature(od.fn).parameters.values())
        required = [p for p in params
                    if p.default is inspect.Parameter.empty
                    and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
        if len(required) > 1:
            missing.append((name, od.category))
    assert not missing, f"ops without sample inputs: {missing}"
