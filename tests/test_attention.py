"""Attention ops/layers + ring-attention sequence parallelism.

Reference test parity: the attention layer gradchecks live in DL4J's
AttentionLayerTest (deeplearning4j-core gradientcheck suite); the op itself is
covered by libnd4j DeclarableOpsTests + SameDiff opvalidation. Ring attention
has NO reference counterpart (SURVEY.md §5.7) — validated against the exact
op on the 8-virtual-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.nn.attention import (
    LearnedSelfAttentionLayer,
    RecurrentAttentionLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.ops import attention as A


def _qkv(rng, b=2, h=2, s=64, d=16, scale=0.3):
    return tuple(
        jnp.asarray(rng.normal(size=(b, h, s, d)) * scale, jnp.float32)
        for _ in range(3)
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact_jnp(self, rng, causal):
        q, k, v = _qkv(rng)
        ref = A.dot_product_attention(q, k, v, causal=causal)
        out = A.flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                                use_pallas=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact_pallas_interpret(self, rng, causal):
        q, k, v = _qkv(rng, s=32, d=8)
        ref = A.dot_product_attention(q, k, v, causal=causal)
        out = A.flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                                use_pallas="interpret")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def test_cross_attention_lengths(self, rng):
        q = jnp.asarray(rng.normal(size=(2, 2, 32, 16)) * 0.3, jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, 2, 64, 16)) * 0.3, jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 2, 64, 16)) * 0.3, jnp.float32)
        ref = A.dot_product_attention(q, k, v, causal=True)
        out = A.flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                                use_pallas=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_exact(self, rng, causal):
        q, k, v = _qkv(rng, s=32, d=8)

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(A.dot_product_attention(q, k, v, causal=causal)))

        def loss_flash(q, k, v):
            return jnp.sum(jnp.sin(A.flash_attention(
                q, k, v, causal=causal, block_q=16, block_k=16, use_pallas=False)))

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-3)

    def test_gradients_fully_masked_rows(self, rng):
        # causal with Sq > Sk: early query rows attend to nothing; their
        # forward output is zero and their gradient mass must be zero too
        q = jnp.asarray(rng.normal(size=(1, 2, 4, 8)) * 0.3, jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 2, 2, 8)) * 0.3, jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 2, 2, 8)) * 0.3, jnp.float32)

        scale = 1.0 / np.sqrt(8)

        def loss_ad(q, k, v):
            # autodiff straight through the blockwise forward (no custom VJP)
            out, _ = A._flash_fwd_jnp(q, k, v, scale, True, 2)
            return jnp.sum(jnp.sin(out))

        def loss_flash(q, k, v):
            return jnp.sum(jnp.sin(A.flash_attention(
                q, k, v, causal=True, block_q=2, block_k=2, use_pallas=False)))

        g_ad = jax.grad(loss_ad, argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ad, g_fl):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-3)

    def test_padding_mask_matches_manual_softmax(self, rng):
        q, k, v = _qkv(rng, s=8, d=4)
        mask = jnp.asarray(rng.integers(0, 2, size=(2, 1, 1, 8)), bool)
        mask = mask.at[..., 0].set(True)
        out = A.dot_product_attention(q, k, v, mask=mask)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(4)
        s = jnp.where(mask, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        np.testing.assert_allclose(out, jnp.einsum("bhqk,bhkv->bhqv", w, v),
                                   atol=1e-6)


class TestMultiHeadOp:
    def test_shapes_and_mask(self, rng):
        b, t, f, hd = 2, 12, 10, 16
        x = jnp.asarray(rng.normal(size=(b, t, f)) * 0.5, jnp.float32)
        Wq, Wk, Wv = (jnp.asarray(rng.normal(size=(f, hd)) * 0.2, jnp.float32)
                      for _ in range(3))
        Wo = jnp.asarray(rng.normal(size=(hd, f)) * 0.2, jnp.float32)
        mask = jnp.ones((b, t)).at[0, 6:].set(0)
        out = A.multi_head_dot_product_attention(
            x, x, x, Wq, Wk, Wv, Wo, n_heads=4, mask=mask)
        assert out.shape == (b, t, f)
        # masked keys/values must not influence valid-row outputs
        x2 = x.at[0, 6:].add(100.0)
        out3 = A.multi_head_dot_product_attention(
            x, x2, x2, Wq, Wk, Wv, Wo, n_heads=4, mask=mask)
        np.testing.assert_allclose(out3[0, :6], out[0, :6], atol=1e-4)


class TestAttentionLayers:
    def test_self_attention_gradcheck(self, rng):
        layer = SelfAttentionLayer(n_in=6, n_out=8, n_heads=2)
        params, state = layer.initialize(jax.random.PRNGKey(0), (5, 6))
        x = jnp.asarray(rng.standard_normal((2, 5, 6)))

        def loss(p):
            y, _ = layer.apply(p, state, x.astype(jax.tree_util.tree_leaves(p)[0].dtype))
            return jnp.sum(y ** 2)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    # tier-1 runtime guard (ISSUE 11 satellite): ~24s fp64 gradcheck
    # through the recurrent-attention scan; test_self_attention_gradcheck
    # covers the attention-layer gradient seam cheaply in tier-1 and the
    # full-suite CI leg still runs this
    @pytest.mark.slow
    def test_recurrent_attention_gradcheck(self, rng):
        layer = RecurrentAttentionLayer(n_in=4, n_out=6, n_heads=2)
        params, state = layer.initialize(jax.random.PRNGKey(1), (5, 4))
        x = jnp.asarray(rng.standard_normal((2, 5, 4)))

        def loss(p):
            y, _ = layer.apply(p, state, x.astype(jax.tree_util.tree_leaves(p)[0].dtype))
            return jnp.sum(y ** 2)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    def test_learned_queries_shape(self, rng):
        layer = LearnedSelfAttentionLayer(n_in=6, n_out=8, n_heads=2, n_queries=3)
        params, state = layer.initialize(jax.random.PRNGKey(0), (10, 6))
        x = jnp.asarray(rng.standard_normal((4, 10, 6)), jnp.float32)
        y, _ = layer.apply(params, state, x)
        assert y.shape == (4, 3, 8)
        assert layer.output_shape((10, 6)) == (3, 8)

    def test_unprojected_requires_square(self):
        with pytest.raises(ValueError):
            SelfAttentionLayer(n_in=4, n_out=6, project_input=False).initialize(
                jax.random.PRNGKey(0), (5, 4))

    def test_self_attention_mask_blocks_padding(self, rng):
        layer = SelfAttentionLayer(n_in=4, n_out=4, n_heads=1)
        params, state = layer.initialize(jax.random.PRNGKey(0), (6, 4))
        x = jnp.asarray(rng.standard_normal((1, 6, 4)), jnp.float32)
        mask = jnp.asarray([[1, 1, 1, 0, 0, 0]], jnp.float32)
        y, _ = layer.apply(params, state, x, mask=mask)
        x2 = x.at[:, 3:].add(50.0)
        y2, _ = layer.apply(params, state, x2, mask=mask)
        np.testing.assert_allclose(y[:, :3], y2[:, :3], atol=1e-4)
        np.testing.assert_allclose(y[:, 3:], 0.0, atol=1e-6)

    def test_in_multilayer_network(self, rng):
        from deeplearning4j_tpu.nn import MultiLayerNetwork
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import GlobalPoolingLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (
            NeuralNetConfiguration.builder()
            .seed(0)
            .updater(Adam(0.01))
            .list()
            .layer(SelfAttentionLayer(n_in=5, n_out=8, n_heads=2))
            .layer(GlobalPoolingLayer(pooling_type="max"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.recurrent(5, 7))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        x = rng.standard_normal((4, 7, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
        s0 = net.score(x=x, y=y)
        for _ in range(30):
            net._fit_batch(x, y)
        assert net.score(x=x, y=y) < s0
        out = net.output(x)
        assert out.shape == (4, 3)


@pytest.mark.multichip
class TestRingAttention:
    def _mesh(self):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:8]).reshape(8), ("seq",))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_exact(self, rng, causal):
        from deeplearning4j_tpu.parallel import ring_attention, shard_sequence

        mesh = self._mesh()
        q, k, v = _qkv(rng, b=2, h=2, s=64, d=8)
        ref = A.dot_product_attention(q, k, v, causal=causal)
        qs, ks, vs = (shard_sequence(t, mesh) for t in (q, k, v))
        out = ring_attention(qs, ks, vs, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-4)

    @pytest.mark.parametrize("seq", [512, 1024])
    def test_long_sequence_8way(self, rng, seq):
        """VERDICT r2 next-round #5: ring attention at seq >= 512 with 8-way
        sequence sharding (64/128 tokens per shard), value-checked vs exact."""
        from deeplearning4j_tpu.parallel import ring_attention, shard_sequence

        mesh = self._mesh()
        q, k, v = _qkv(rng, b=1, h=2, s=seq, d=16)
        ref = A.dot_product_attention(q, k, v, causal=True)
        qs, ks, vs = (shard_sequence(t, mesh) for t in (q, k, v))
        out = jax.jit(
            lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
        )(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), ref, atol=5e-5, rtol=1e-3)

    def test_gradients_match_exact(self, rng):
        from deeplearning4j_tpu.parallel import ring_attention, shard_sequence

        mesh = self._mesh()
        q, k, v = _qkv(rng, b=1, h=2, s=32, d=8)

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(A.dot_product_attention(q, k, v, causal=True)))

        def loss_ring(q, k, v):
            return jnp.sum(jnp.sin(ring_attention(q, k, v, mesh, causal=True)))

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        qs, ks, vs = (shard_sequence(t, mesh) for t in (q, k, v))
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qs, ks, vs)
        for a, b in zip(g_ref, g_ring):
            np.testing.assert_allclose(np.asarray(b), a, atol=5e-5, rtol=1e-3)


class TestFlashAutoDispatch:
    """Auto-dispatch by the measured crossover (BASELINE.md round-3 table)."""

    def test_resolve_flash_rules(self):
        rf = A.resolve_flash
        # full [B,1|H,Tq,Tk] attention masks force the exact path; (B,Tk)
        # PADDING masks are flash-eligible since r14 (the kernel masks key
        # blocks in-place — equivalence pinned in tests/test_kernels.py)
        assert rf(True, 4096, 4096,
                  mask=jnp.ones((2, 1, 4096, 4096))) is False
        assert rf(True, 4096, 4096, mask=jnp.ones((2, 4096))) is True
        # explicit booleans are respected
        assert rf(True, 128, 128) is True
        assert rf(False, 4096, 4096) is False
        # "auto" on CPU never picks the (jnp fallback) flash path
        assert rf("auto", 4096, 4096) is (jax.default_backend() == "tpu")
        assert rf("auto", 128, 128) is False  # below crossover everywhere

    def test_mha_auto_matches_exact(self, rng):
        """flash="auto" (default) must be numerically identical to the exact
        path at short seq — it IS the exact path below the crossover."""
        F, H = 8, 2
        x = jnp.asarray(rng.normal(size=(2, 6, F)).astype(np.float32))
        Ws = [jnp.asarray(rng.normal(size=(F, F)).astype(np.float32) * 0.3)
              for _ in range(4)]
        auto = A.multi_head_dot_product_attention(x, x, x, *Ws, n_heads=H)
        exact = A.multi_head_dot_product_attention(x, x, x, *Ws, n_heads=H,
                                                   flash=False)
        np.testing.assert_allclose(np.asarray(auto), np.asarray(exact))

    def test_resolve_flash_rejects_typos(self):
        with pytest.raises(ValueError, match="flash"):
            A.resolve_flash("Auto", 2048, 2048)

    def test_sequence_mask_jit_needs_maxlen(self):
        from deeplearning4j_tpu import ops
        with pytest.raises(ValueError, match="maxlen"):
            jax.jit(lambda l: ops.exec_op("sequence_mask", l))(
                jnp.asarray([1, 3]))
        m = jax.jit(lambda l: ops.exec_op("sequence_mask", l, 4))(
            jnp.asarray([1, 3]))
        assert m.shape == (2, 4)
