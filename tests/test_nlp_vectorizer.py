"""BagOfWords / TF-IDF vectorizer tests (reference
org.deeplearning4j.bagofwords.vectorizer.* test parity)."""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import BagOfWordsVectorizer, TfidfVectorizer

DOCS = [
    "the cat sat on the mat",
    "the dog sat on the log",
    "cats and dogs",
]


class TestBagOfWords:
    def test_counts(self):
        v = BagOfWordsVectorizer().fit(DOCS)
        x = v.transform(DOCS[0])
        assert x[v.index_of("the")] == 2.0
        assert x[v.index_of("cat")] == 1.0
        assert x[v.index_of("dog")] == 0.0

    def test_min_word_frequency(self):
        v = BagOfWordsVectorizer(min_word_frequency=2).fit(DOCS)
        assert v.index_of("the") >= 0      # appears 4x
        assert v.index_of("sat") >= 0      # 2x
        assert v.index_of("cats") == -1    # 1x — filtered

    def test_fit_transform_matrix(self):
        v = BagOfWordsVectorizer()
        m = v.fit_transform(DOCS)
        assert m.shape == (3, len(v.vocab))
        np.testing.assert_allclose(m[0], v.transform(DOCS[0]))

    def test_vectorize_with_labels(self):
        v = BagOfWordsVectorizer().fit(DOCS, labels=["pet", "pet", "both"])
        x, y = v.vectorize(DOCS[2], "both")
        assert y.tolist() == [1.0, 0.0]    # labels sorted: both, pet
        with pytest.raises(ValueError):
            v.vectorize(DOCS[0], "unknown")


class TestTfidf:
    def test_weighting_formula(self):
        v = TfidfVectorizer().fit(DOCS)
        x = v.transform(DOCS[0])
        # "cat": tf=1, df=1, N=3 -> log10(3)
        np.testing.assert_allclose(x[v.index_of("cat")], math.log10(3.0),
                                   rtol=1e-6)
        # "the": tf=2, df=2 -> 2*log10(1.5)
        np.testing.assert_allclose(x[v.index_of("the")],
                                   2 * math.log10(1.5), rtol=1e-6)
        # word in every doc of a 3-doc corpus: idf = log10(1) = 0
        v2 = TfidfVectorizer().fit(["a b", "a c", "a d"])
        assert v2.transform("a a")[v2.index_of("a")] == 0.0

    def test_tfidf_word_helper(self):
        v = TfidfVectorizer().fit(DOCS)
        np.testing.assert_allclose(v.tfidf_word("cat", 2),
                                   2 * math.log10(3.0), rtol=1e-6)
        assert v.tfidf_word("missing", 5) == 0.0

    def test_unseen_words_ignored(self):
        v = TfidfVectorizer().fit(DOCS)
        x = v.transform("zebra quagga")
        np.testing.assert_allclose(x, 0.0)


class TestTfidfRecordReader:
    def test_directory_corpus(self, tmp_path):
        from deeplearning4j_tpu.datavec import TfidfRecordReader

        (tmp_path / "pos").mkdir()
        (tmp_path / "neg").mkdir()
        (tmp_path / "pos" / "a.txt").write_text("good great good")
        (tmp_path / "neg" / "b.txt").write_text("bad awful")
        rr = TfidfRecordReader(str(tmp_path))
        recs = list(rr)
        assert len(recs) == 2 and rr.labels() == ["neg", "pos"]
        vocab_n = len(rr.vectorizer.vocab)
        assert all(len(r) == vocab_n + 1 for r in recs)
        # label index appended; tf-idf of "good" (tf=2, df=1, N=2)
        import math

        pos_row = [r for r in recs if r[-1] == 1][0]
        gi = rr.vectorizer.index_of("good")
        np.testing.assert_allclose(pos_row[gi], 2 * math.log10(2.0),
                                   rtol=1e-6)

    def test_explicit_documents(self):
        from deeplearning4j_tpu.datavec import TfidfRecordReader

        rr = TfidfRecordReader(documents=[("x y", "a"), ("y z", "b")],
                               append_label=False)
        recs = list(rr)
        assert len(recs[0]) == len(rr.vectorizer.vocab)
