"""Updater (learning-rule) tests — semantics vs hand-computed references,
schedule behavior, serialization round-trips."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import schedules as sched
from deeplearning4j_tpu.nn import updaters as upd


def _step_n(updater, params, grads_fn, n):
    state = updater.init_state(params)
    for it in range(n):
        params, state = upd.apply_updater(updater, params, grads_fn(params), state, it)
    return params, state


def test_sgd_matches_manual():
    u = upd.Sgd(learning_rate=0.1)
    p = {"w": jnp.array([1.0, 2.0])}
    g = {"w": jnp.array([0.5, -1.0])}
    new_p, _ = upd.apply_updater(u, p, g, u.init_state(p), 0)
    np.testing.assert_allclose(new_p["w"], [0.95, 2.1], rtol=1e-6)


def test_adam_first_step_is_lr_sized():
    # After one step, Adam's bias-corrected update ≈ lr * sign(g).
    u = upd.Adam(learning_rate=0.001)
    p = {"w": jnp.zeros(3)}
    g = {"w": jnp.array([1.0, -2.0, 0.5])}
    new_p, _ = upd.apply_updater(u, p, g, u.init_state(p), 0)
    np.testing.assert_allclose(new_p["w"], [-0.001, 0.001, -0.001], rtol=1e-3)


def test_nesterovs_momentum_accumulates():
    u = upd.Nesterovs(learning_rate=0.1, momentum=0.9)
    p = {"w": jnp.array([0.0])}
    const_g = lambda _: {"w": jnp.array([1.0])}
    p1, _ = _step_n(u, p, const_g, 1)
    p10, _ = _step_n(u, p, const_g, 10)
    # With momentum, 10 steps move much further than 10x the first step.
    assert abs(float(p10["w"][0])) > 5 * abs(float(p1["w"][0]))


def test_adagrad_decreasing_effective_rate():
    u = upd.AdaGrad(learning_rate=0.1)
    p = {"w": jnp.array([0.0])}
    state = u.init_state(p)
    const_g = {"w": jnp.array([1.0])}
    steps = []
    for it in range(3):
        new_p, state = upd.apply_updater(u, p, const_g, state, it)
        steps.append(abs(float(new_p["w"][0] - p["w"][0])))
        p = new_p
    assert steps[0] > steps[1] > steps[2]


def test_rmsprop_scale_invariance():
    # RmsProp normalizes by gradient magnitude: big and small gradients give
    # comparable step sizes after warm-up.
    u = upd.RmsProp(learning_rate=0.01)
    big, _ = _step_n(u, {"w": jnp.array([0.0])}, lambda _: {"w": jnp.array([1e3])}, 5)
    small, _ = _step_n(u, {"w": jnp.array([0.0])}, lambda _: {"w": jnp.array([1e-3])}, 5)
    ratio = abs(float(big["w"][0])) / abs(float(small["w"][0]))
    assert 0.5 < ratio < 2.0


def test_amsgrad_vhat_monotone():
    u = upd.AMSGrad(learning_rate=0.01)
    p = {"w": jnp.array([0.0])}
    state = u.init_state(p)
    _, state = upd.apply_updater(u, p, {"w": jnp.array([10.0])}, state, 0)
    vhat_after_big = float(state["vhat"]["w"][0])
    _, state = upd.apply_updater(u, p, {"w": jnp.array([0.01])}, state, 1)
    assert float(state["vhat"]["w"][0]) >= vhat_after_big * 0.99


def test_adamw_decays_weights():
    u = upd.AdamW(learning_rate=0.01, weight_decay=0.1)
    p = {"w": jnp.array([100.0])}
    new_p, _ = upd.apply_updater(u, p, {"w": jnp.array([0.0])}, u.init_state(p), 0)
    assert float(new_p["w"][0]) < 100.0  # decay applies even with zero grad


def test_noop_freezes():
    u = upd.NoOp()
    p = {"w": jnp.array([1.0])}
    new_p, _ = upd.apply_updater(u, p, {"w": jnp.array([123.0])}, u.init_state(p), 0)
    np.testing.assert_array_equal(new_p["w"], p["w"])


def test_all_updaters_reduce_quadratic_loss():
    # opt min at w=3; every updater should move toward it.
    import jax

    target = jnp.array([3.0, -2.0])

    def grads(p):
        return {"w": 2 * (p["w"] - target)}

    for u, steps in [
        (upd.Sgd(0.05), 50), (upd.Adam(0.05), 50), (upd.Nesterovs(0.02), 50),
        (upd.AdaGrad(0.5), 50), (upd.RmsProp(0.05), 50),
        # AdaDelta's unit-free steps ramp up slowly by design — needs more steps.
        (upd.AdaDelta(), 500),
        (upd.AMSGrad(0.05), 50), (upd.AdaMax(0.05), 50), (upd.Nadam(0.05), 50),
    ]:
        p = {"w": jnp.zeros(2)}
        start = float(jnp.sum((p["w"] - target) ** 2))
        p, _ = _step_n(u, p, grads, steps)
        end = float(jnp.sum((p["w"] - target) ** 2))
        assert end < start * 0.5, f"{type(u).__name__} failed to descend: {start}->{end}"


def test_step_schedule():
    s = sched.StepSchedule(initial_value=1.0, decay_rate=0.5, step=10)
    assert float(s(0)) == 1.0
    assert float(s(10)) == 0.5
    assert float(s(25)) == 0.25


def test_poly_and_sigmoid_schedules():
    p = sched.PolySchedule(initial_value=1.0, power=2.0, max_iter=100)
    assert float(p(0)) == 1.0
    np.testing.assert_allclose(float(p(50)), 0.25, rtol=1e-6)
    assert float(p(100)) == 0.0
    s = sched.SigmoidSchedule(initial_value=1.0, gamma=1.0, step_size=10)
    assert float(s(10)) == 0.5


def test_warmup_cosine():
    s = sched.WarmupCosineSchedule(peak_value=1.0, warmup_steps=10, total_steps=110)
    np.testing.assert_allclose(float(s(5)), 0.5, rtol=1e-5)
    np.testing.assert_allclose(float(s(10)), 1.0, rtol=1e-5)
    np.testing.assert_allclose(float(s(110)), 0.0, atol=1e-6)


def test_map_schedule():
    s = sched.MapSchedule(values={0: 1.0, 100: 0.1, 200: 0.01})
    assert float(s(50)) == 1.0
    assert float(s(150)) == pytest.approx(0.1)
    assert float(s(500)) == pytest.approx(0.01)


def test_updater_serialization_roundtrip():
    u = upd.Adam(learning_rate=sched.StepSchedule(0.001, 0.9, 1000), beta1=0.85)
    d = u.to_dict()
    u2 = upd.updater_from_dict(d)
    assert u2.beta1 == 0.85
    assert isinstance(u2.learning_rate, sched.StepSchedule)
    assert float(u2.lr(1000)) == pytest.approx(0.0009)


def test_updater_traceable_under_jit():
    import jax

    u = upd.Adam(learning_rate=sched.PolySchedule(0.01, 1.0, 100))
    p = {"w": jnp.ones(4)}
    state = u.init_state(p)

    @jax.jit
    def step(p, state, it):
        return upd.apply_updater(u, p, {"w": jnp.ones(4)}, state, it)

    p1, s1 = step(p, state, 0)
    p2, s2 = step(p1, s1, 1)
    assert float(p2["w"][0]) < float(p1["w"][0]) < 1.0
