"""GSPMD sharded-fit bit-identity + ZeRO memory + sharded cost report.

The deterministic lane mode (parallel/gspmd.py) makes an 8-virtual-device
sharded fit BIT-identical to the single-device fit — params, Adam moments,
and the RNG key — because both topologies execute the SAME vmapped lane
program, cross-lane combines are explicit pairwise-tree adds GSPMD cannot
re-associate, and the step is staged as three jit programs so LLVM FMA
contraction can never fuse a lane-weight multiply into the tree adds (the
determinism note in parallel/wrapper.py).

Known backend boundary, pinned below: XLA:CPU lowers the vmapped conv
FILTER gradient to a batch-grouped convolution whose accumulation grouping
depends on the lane fold (and gemm k-blocking is shape-dependent for
contraction dims >= ~1024) — conv topologies reproduce to ~1e-5 instead of
bit-exactly (docs/DISTRIBUTED.md).
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh, gspmd


def _mesh8():
    return TrainingMesh(data=8)


def _mesh1():
    return TrainingMesh(data=1, devices=jax.devices()[:1])


def _leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


def _assert_tree_equal(a, b, what):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for i, (u, v) in enumerate(zip(la, lb)):
        assert u.shape == v.shape, (what, i)
        assert (u == v).all(), (
            f"{what} leaf {i} differs: maxdiff "
            f"{np.abs(u.astype(np.float64) - v.astype(np.float64)).max()}")


def _fit_pair(make_net, data_iter_fn, epochs=2, replicas=8):
    """Fit the same net on a 1-device and an 8-device deterministic wrapper
    (same lane count) and return both nets."""
    nets = []
    for mesh in (_mesh1(), _mesh8()):
        net = make_net()
        pw = ParallelWrapper(net, mesh=mesh, deterministic=True,
                             replicas=replicas, skew_every=0)
        pw.fit(data_iter_fn(), epochs=epochs)
        nets.append(net)
    return nets


def _dense_mln():
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_in=6, n_out=32, activation="relu"))
            .layer(DenseLayer(n_in=32, n_out=32, activation="tanh"))
            .layer(OutputLayer(n_in=32, n_out=4, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


@pytest.mark.multichip
class TestBitIdentityMLN:
    def test_dense_fit_bit_identical(self, rng):
        xs = rng.standard_normal((64, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
        n1, n8 = _fit_pair(
            _dense_mln, lambda: ArrayDataSetIterator(xs, ys, batch=32))
        _assert_tree_equal(n1.params, n8.params, "params")
        _assert_tree_equal(n1.opt_states, n8.opt_states, "adam moments")
        _assert_tree_equal(n1.states, n8.states, "states")
        np.testing.assert_array_equal(np.asarray(n1._rng_key),
                                      np.asarray(n8._rng_key))
        assert n1.iteration == n8.iteration

    def test_ragged_bucketed_batch_bit_identical(self, rng):
        # global batch 20 on 8 lanes: pads to 24 with 0-weighted rows; the
        # weighted-lane recombination must keep the 1-dev and 8-dev runs
        # identical AND both finite
        xs = rng.standard_normal((20, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 20)]
        n1, n8 = _fit_pair(
            _dense_mln, lambda: [DataSet(xs, ys)], epochs=3)
        _assert_tree_equal(n1.params, n8.params, "params(ragged)")
        _assert_tree_equal(n1.opt_states, n8.opt_states, "moments(ragged)")
        assert np.isfinite(float(n8.score_value))

    def test_zero_optimizer_composes_with_identity(self, rng):
        # ZeRO sharding the moments must not change a single bit (Adam is
        # elementwise) — the 8-dev run here has zero_optimizer on (default)
        xs = rng.standard_normal((32, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]

        net1 = _dense_mln()
        ParallelWrapper(net1, mesh=_mesh1(), deterministic=True, replicas=8,
                        skew_every=0).fit([DataSet(xs, ys)], epochs=2)
        net8 = _dense_mln()
        pw8 = ParallelWrapper(net8, mesh=_mesh8(), deterministic=True,
                              replicas=8, zero_optimizer=True, skew_every=0)
        pw8.fit([DataSet(xs, ys)], epochs=2)
        _assert_tree_equal(net1.opt_states, net8.opt_states, "zero moments")
        # and the moments really are distributed
        frac = gspmd.sharded_fraction(pw8._zero_specs)
        assert frac > 0.0, pw8.layout["opt_states"]


def _lstm_mln(tbptt=8):
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(0.01))
            .tbptt_length(tbptt)
            .list()
            .layer(LSTM(n_in=5, n_out=24))
            .layer(RnnOutputLayer(n_in=24, n_out=3, loss="mcxent",
                                  activation="softmax"))
            .set_input_type(InputType.recurrent(5, 16))
            .build())
    return MultiLayerNetwork(conf).init()


@pytest.mark.multichip
class TestBitIdentityTBPTT:
    def test_tbptt_segments_bit_identical(self, rng):
        """16-step sequences with tbptt_length=8: two lane-decomposed
        segment updates per batch, carries lane-stacked across segments —
        params, Adam moments and the RNG key must match the single-device
        run exactly."""
        xs = rng.standard_normal((16, 16, 5)).astype(np.float32)
        ids = rng.integers(0, 3, size=(16, 16))
        ys = np.eye(3, dtype=np.float32)[ids]
        n1, n8 = _fit_pair(
            _lstm_mln, lambda: [DataSet(xs, ys)], epochs=2)
        assert n1.iteration == n8.iteration == 4  # 2 segments x 2 epochs
        _assert_tree_equal(n1.params, n8.params, "params(tbptt)")
        _assert_tree_equal(n1.opt_states, n8.opt_states, "moments(tbptt)")
        np.testing.assert_array_equal(np.asarray(n1._rng_key),
                                      np.asarray(n8._rng_key))


def _dense_cg():
    from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.nn.vertices import MergeVertex

    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
            .graph_builder()
            .add_inputs("ina", "inb")
            .add_layer("da", DenseLayer(n_in=4, n_out=16,
                                        activation="relu"), "ina")
            .add_layer("db", DenseLayer(n_in=3, n_out=16,
                                        activation="relu"), "inb")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer("out1", OutputLayer(n_in=32, n_out=2, loss="mcxent",
                                           activation="softmax"), "m")
            .add_layer("out2", OutputLayer(n_in=32, n_out=3, loss="mcxent",
                                           activation="softmax"), "m")
            .set_outputs("out1", "out2")
            .set_input_types(InputType.feed_forward(4),
                             InputType.feed_forward(3))
            .build())
    from deeplearning4j_tpu.nn import ComputationGraph as CG

    return CG(conf).init()


@pytest.mark.multichip
class TestBitIdentityCG:
    def test_multi_io_graph_fit_bit_identical(self, rng):
        from deeplearning4j_tpu.data import MultiDataSet

        xa = rng.standard_normal((24, 4)).astype(np.float32)
        xb = rng.standard_normal((24, 3)).astype(np.float32)
        y1 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 24)]
        y2 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
        mds = MultiDataSet(features=[xa, xb], labels=[y1, y2])
        n1, n8 = _fit_pair(_dense_cg, lambda: [mds], epochs=3)
        _assert_tree_equal(n1.params, n8.params, "cg params")
        _assert_tree_equal(n1.opt_states, n8.opt_states, "cg moments")
        np.testing.assert_array_equal(np.asarray(n1._rng_key),
                                      np.asarray(n8._rng_key))


def _conv_mln():
    """Flagship-topology family: conv + batchnorm + pool + dense head."""
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import (BatchNormalization,
                                              ConvolutionLayer, DenseLayer,
                                              OutputLayer, SubsamplingLayer)
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(0.01))
            .list()
            .layer(ConvolutionLayer(n_in=3, n_out=8, kernel_size=(3, 3),
                                    padding="SAME", activation="relu"))
            .layer(BatchNormalization(n_out=8))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_in=8, n_out=8, kernel_size=(3, 3),
                                    padding="SAME", activation="relu"))
            .layer(OutputLayer(n_in=8 * 6 * 6, n_out=4, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.convolutional(12, 12, 3)).build())
    return MultiLayerNetwork(conf).init()


@pytest.mark.multichip
class TestFlagshipTopology:
    def test_conv_bn_fit_reproduces(self, rng):
        """Conv topologies: everything except the conv FILTER gradient is
        exact; XLA:CPU lowers that one op to a batch-grouped conv whose
        accumulation grouping depends on the lane fold (pinned boundary —
        docs/DISTRIBUTED.md). The fit must reproduce to float tolerance
        and the non-conv state exactly."""
        xs = rng.standard_normal((32, 12, 12, 3)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
        n1, n8 = _fit_pair(
            _conv_mln, lambda: [DataSet(xs, ys)], epochs=2)
        np.testing.assert_array_equal(np.asarray(n1._rng_key),
                                      np.asarray(n8._rng_key))
        for a, b in zip(_leaves(n1.params), _leaves(n8.params)):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4)
        for a, b in zip(_leaves(n1.opt_states), _leaves(n8.opt_states)):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4)


@pytest.mark.multichip
class TestZeroMemory:
    def test_optimizer_state_bytes_shrink(self, rng):
        """ZeRO satellite: Adam moment bytes/device drop ~Nx on the 8-way
        mesh (every weight matrix of this net has an 8-divisible dim)."""
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.01))
                .list()
                .layer(DenseLayer(n_in=256, n_out=512, activation="relu"))
                .layer(DenseLayer(n_in=512, n_out=512, activation="relu"))
                .layer(OutputLayer(n_in=512, n_out=16, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(256)).build())
        net = MultiLayerNetwork(conf).init()
        replicated_bytes = gspmd.tree_bytes(net.opt_states)

        pw = ParallelWrapper(net, mesh=_mesh8(), zero_optimizer=True,
                             skew_every=0)
        xs = rng.standard_normal((32, 256)).astype(np.float32)
        ys = np.eye(16, dtype=np.float32)[rng.integers(0, 16, 32)]
        pw.fit([DataSet(xs, ys)], epochs=1)
        per_dev = pw.opt_state_bytes_per_device()
        # biases and tiny leaves stay replicated; the big moment matrices
        # shard 8-ways -> well under 1/4 of the replicated footprint
        assert per_dev < replicated_bytes / 4, (per_dev, replicated_bytes)
        assert np.isfinite(float(net.score_value))

    def test_zero_off_keeps_state_replicated(self, rng):
        net = _dense_mln()
        pw = ParallelWrapper(net, mesh=_mesh8(), zero_optimizer=False,
                             skew_every=0)
        xs = rng.standard_normal((16, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
        pw.fit([DataSet(xs, ys)], epochs=1)
        assert pw.opt_state_bytes_per_device() == gspmd.tree_bytes(
            net.opt_states)


@pytest.mark.multichip
class TestLayoutAndReshard:
    def test_layout_signature_and_gauges(self, rng):
        net = _dense_mln()
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        xs = rng.standard_normal((16, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
        pw.fit([DataSet(xs, ys)], epochs=1)
        assert "data=8" in pw.layout["signature"]
        assert pw.layout["opt_states"], pw.layout
        # layout signatures key executables: a different mesh is a
        # different signature (and a different jit dispatch entry)
        assert _mesh8().layout_signature() != _mesh1().layout_signature()

    def test_reshard_onto_smaller_mesh_continues(self, rng):
        """Elastic regroup hook: mid-fit re-shard 8 -> 4 devices re-places
        params/ZeRO state and recompiles; training continues and the loss
        stays finite (values equivalent up to fp association)."""
        net = _dense_mln()
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        xs = rng.standard_normal((32, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
        pw.fit([DataSet(xs, ys)], epochs=2)
        s_before = float(net.score_value)
        pw.reshard(TrainingMesh(data=4, devices=jax.devices()[:4]))
        assert pw.mesh.data == 4
        pw.fit([DataSet(xs, ys)], epochs=4)
        assert np.isfinite(float(net.score_value))
        assert float(net.score_value) < s_before  # still learning


@pytest.mark.multichip
class TestShardedCostReport:
    def test_per_device_and_global_totals(self, rng):
        """cost_analysis() of a GSPMD executable is per-device: the sharded
        report must expose devices + global totals, and the per-device
        FLOPs must be ~1/8 of the single-device program's (collectives add
        a little, padding none — band is loose on purpose)."""
        net = _dense_mln()
        single = net.cost_report(batch_size=64, publish=False)
        pw = ParallelWrapper(net, mesh=_mesh8(), skew_every=0)
        xs = rng.standard_normal((64, 6)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
        pw.fit([DataSet(xs, ys)], epochs=1)
        rep = pw.cost_report(batch_size=64, publish=False)
        assert rep.devices == 8
        assert rep.flops_per_step_global == rep.flops_per_step * 8
        assert rep.totals_global["flops"] == rep.totals["flops"] * 8
        if rep.source == "xla" and single.source == "xla":
            ratio = rep.flops_per_step / (single.flops_per_step / 8)
            assert 0.7 < ratio < 1.8, (rep.flops_per_step,
                                       single.flops_per_step)
        assert "PER-DEVICE" in rep.summary()
