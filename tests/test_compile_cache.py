"""Compile-once execution subsystem (docs/COMPILE_CACHE.md): shape
bucketing, recompile-count regression, bit-identity of bucketed vs unpadded
execution, AOT warmup, the persistent compilation cache, the SameDiff
cross-instance executable cache, and recompile observability."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator, BucketingPolicy
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import (
    ComputationGraph, ComputationGraphConfiguration)
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.util import get_watcher

R = np.random.default_rng(42)


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(bool((x == y).all()) for x, y in zip(la, lb))


def _mlp(seed=7, buckets=None, seq=None, tbptt=0, recurrent=False):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
    if buckets is not None:
        b = b.batch_buckets(buckets)
    if seq is not None:
        b = b.seq_buckets(seq)
    if tbptt:
        b = b.tbptt_length(tbptt)
    lb = b.list()
    if recurrent:
        conf = (lb.layer(LSTM(n_in=6, n_out=8))
                .layer(RnnOutputLayer(n_in=8, n_out=3))
                .set_input_type(InputType.recurrent(6, 12)).build())
    else:
        conf = (lb.layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=5))
                .set_input_type(InputType.feed_forward(12)).build())
    return MultiLayerNetwork(conf).init()


def _cg(seed=3, buckets=None):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
    if buckets is not None:
        b = b.batch_buckets(buckets)
    g = (b.graph_builder().add_inputs("in")
         .add_layer("d1", DenseLayer(n_in=10, n_out=14, activation="tanh"),
                    "in")
         .add_layer("d2", DenseLayer(n_in=10, n_out=14, activation="relu"),
                    "in")
         .add_layer("out", OutputLayer(n_in=28, n_out=4), "d1", "d2")
         .set_outputs("out").set_input_types((10,)).build())
    return ComputationGraph(g).init()


def _dense_data(n=21, f=12, c=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
    return x, y


# ---------------------------------------------------------------------------
# BucketingPolicy unit behavior
# ---------------------------------------------------------------------------
class TestBucketingPolicy:
    def test_pow2_rounding(self):
        p = BucketingPolicy(batch_buckets="pow2")
        assert [p.bucket_batch(n) for n in (1, 2, 3, 5, 8, 9, 33)] == \
            [1, 2, 4, 8, 8, 16, 64]

    def test_explicit_rounding_and_passthrough(self):
        p = BucketingPolicy(batch_buckets=(8, 16, 32))
        assert p.bucket_batch(5) == 8
        assert p.bucket_batch(16) == 16
        assert p.bucket_batch(17) == 32
        # above the largest bucket: pass through unpadded (own compile)
        assert p.bucket_batch(100) == 100

    def test_spec_round_trip(self):
        p = BucketingPolicy.from_spec("batch=8,16;seq=pow2")
        assert p.batch_buckets == (8, 16)
        assert p.seq_buckets == "pow2"
        assert BucketingPolicy.from_spec(p.to_spec()) == p
        assert BucketingPolicy.from_spec("pow2").batch_buckets == "pow2"
        assert BucketingPolicy.from_spec("") is None
        assert BucketingPolicy.from_spec("none") is None

    def test_bad_specs_fail_fast(self):
        with pytest.raises(ValueError):
            BucketingPolicy.from_spec("batch=abc")
        with pytest.raises(ValueError):
            BucketingPolicy.from_spec("time=8")
        with pytest.raises(ValueError):
            BucketingPolicy(batch_buckets="fib")
        with pytest.raises(ValueError):
            BucketingPolicy(batch_buckets=(0, 8))

    def test_pad_batch_weights(self):
        p = BucketingPolicy(batch_buckets=(8,))
        x, y = _dense_data(n=5)
        xp, yp, mask, lmask, w = p.pad_batch(x, y)
        assert xp.shape == (8, 12) and yp.shape == (8, 5)
        np.testing.assert_array_equal(w, [1, 1, 1, 1, 1, 0, 0, 0])
        np.testing.assert_array_equal(xp[:5], x)
        assert (xp[5:] == 0).all() and (yp[5:] == 0).all()
        # full batch: no padding but the weights vector is still attached
        x8, y8 = _dense_data(n=8)
        xp, yp, _, _, w = p.pad_batch(x8, y8)
        assert xp.shape == (8, 12) and (w == 1).all()

    def test_conf_json_round_trip_mln(self):
        conf = (NeuralNetConfiguration.builder().batch_buckets((8, 16))
                .seq_buckets("pow2").list()
                .layer(DenseLayer(n_in=4, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.batch_buckets == (8, 16)
        assert back.seq_buckets == "pow2"

    def test_conf_json_round_trip_cg(self):
        g = (NeuralNetConfiguration.builder().batch_buckets("pow2")
             .graph_builder().add_inputs("in")
             .add_layer("out", OutputLayer(n_in=4, n_out=2), "in")
             .set_outputs("out").set_input_types((4,)).build())
        back = ComputationGraphConfiguration.from_json(g.to_json())
        assert back.batch_buckets == "pow2"
        assert back.seq_buckets is None

    def test_env_default(self, monkeypatch):
        from deeplearning4j_tpu.config import Environment

        monkeypatch.setenv("DL4J_TPU_BUCKETS", "batch=4,8")
        old = Environment._instance
        Environment._instance = None
        try:
            conf = (NeuralNetConfiguration.builder().list()
                    .layer(OutputLayer(n_in=4, n_out=2))
                    .set_input_type(InputType.feed_forward(4)).build())
            assert conf.batch_buckets == (4, 8)
        finally:
            Environment._instance = old

    def test_env_default_bad_spec_fails_fast(self, monkeypatch):
        from deeplearning4j_tpu.config import Environment

        monkeypatch.setenv("DL4J_TPU_BUCKETS", "batch=nope")
        old = Environment._instance
        Environment._instance = None
        try:
            with pytest.raises(ValueError, match="DL4J_TPU_BUCKETS"):
                NeuralNetConfiguration.builder()
        finally:
            Environment._instance = old


# ---------------------------------------------------------------------------
# Recompile-count regression: exactly N traces for a fixed bucket set
# ---------------------------------------------------------------------------
class TestRecompileCounts:
    def test_mln_ragged_epoch_traces(self):
        x, y = _dense_data(n=21)  # 21 % 8 = 5: ragged tail
        w = get_watcher()
        net = _mlp(buckets=None)
        with w.scope() as s:
            net.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            unbucketed = s.traces_of("MultiLayerNetwork.train_step")
        net = _mlp(buckets=(8,))
        with w.scope() as s:
            net.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            bucketed = s.traces_of("MultiLayerNetwork.train_step")
        assert unbucketed == 2  # full batch + ragged tail
        assert bucketed == 1    # ragged tail lands on the full-batch bucket

    def test_cg_ragged_epoch_traces(self):
        x, y = _dense_data(n=19, f=10, c=4)
        w = get_watcher()
        g = _cg(buckets=None)
        with w.scope() as s:
            g.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            unbucketed = s.traces_of("ComputationGraph.train_step")
        g = _cg(buckets=(8,))
        with w.scope() as s:
            g.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            bucketed = s.traces_of("ComputationGraph.train_step")
        assert unbucketed == 2
        assert bucketed == 1

    def test_bucket_set_bounds_traces_across_many_sizes(self):
        """Explicit bucket set {4, 8}: batches of size 1..8 in one run must
        compile at most twice (per-shape attribution in the watcher)."""
        w = get_watcher()
        net = _mlp(buckets=(4, 8))
        rng = np.random.default_rng(5)
        before = dict(w.shapes.get("MultiLayerNetwork.train_step", {}))
        with w.scope() as s:
            for n in (3, 1, 4, 7, 8, 2, 5, 6):
                x = rng.normal(size=(n, 12)).astype(np.float32)
                y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, n)]
                net._fit_batch(x, y)
            assert s.traces_of("MultiLayerNetwork.train_step") == 2
        new = [sig for sig, n in
               w.shapes["MultiLayerNetwork.train_step"].items()
               if n > before.get(sig, 0)]
        assert sorted(sig[0][0][0] for sig in new) == [4, 8]

    def test_tbptt_remainder_traces(self):
        xt = R.normal(size=(8, 11, 6)).astype(np.float32)  # k=4: segs 4,4,3
        yt = np.eye(3, dtype=np.float32)[
            R.integers(0, 3, (8, 11))].astype(np.float32)
        w = get_watcher()
        net = _mlp(seed=11, tbptt=4, recurrent=True)
        with w.scope() as s:
            net.fit(DataSet(xt, yt))
            unbucketed = s.traces_of("MultiLayerNetwork.tbptt_step")
        net = _mlp(seed=11, tbptt=4, recurrent=True, buckets=(8,),
                   seq=(4,))
        with w.scope() as s:
            net.fit(DataSet(xt, yt))
            bucketed = s.traces_of("MultiLayerNetwork.tbptt_step")
        assert unbucketed == 2  # full segment + length-3 remainder
        assert bucketed == 1    # remainder pads onto the (B, k) signature


# ---------------------------------------------------------------------------
# Bit-identity: bucketed == unpadded trajectories and metrics
# ---------------------------------------------------------------------------
class TestBitIdentity:
    def test_mln_fit_trajectory_and_evaluate(self):
        x, y = _dense_data(n=21)
        it = lambda: ArrayDataSetIterator(x, y, batch=8)  # noqa: E731
        a = _mlp(buckets=None)
        b = _mlp(buckets=(8,))
        a.fit(it(), epochs=3)
        b.fit(it(), epochs=3)
        assert _leaves_equal(a.params, b.params)
        assert float(a.score_value) == float(b.score_value)
        ea, eb = a.evaluate(it()), b.evaluate(it())
        assert ea.accuracy() == eb.accuracy()
        assert ea.f1() == eb.f1()
        # score() on a ragged batch (pads + weights) matches exactly
        assert a.score(x=x[:5], y=y[:5]) == b.score(x=x[:5], y=y[:5])
        # output() on a ragged batch: rows are sliced back, bit-equal
        np.testing.assert_array_equal(np.asarray(a.output(x[:3])),
                                      np.asarray(b.output(x[:3])))

    def test_cg_fit_trajectory_and_evaluate(self):
        x, y = _dense_data(n=19, f=10, c=4)
        it = lambda: ArrayDataSetIterator(x, y, batch=8)  # noqa: E731
        a = _cg(buckets=None)
        b = _cg(buckets=(8,))
        a.fit(it(), epochs=3)
        b.fit(it(), epochs=3)
        assert _leaves_equal(a.params, b.params)
        assert a.evaluate(it()).accuracy() == b.evaluate(it()).accuracy()
        assert a.score(x=x[:4], y=y[:4]) == b.score(x=x[:4], y=y[:4])

    def test_lstm_batch_bucketing(self):
        x = R.normal(size=(13, 12, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            R.integers(0, 3, (13, 12))].astype(np.float32)
        it = lambda: ArrayDataSetIterator(x, y, batch=8)  # noqa: E731
        a = _mlp(recurrent=True, buckets=None)
        b = _mlp(recurrent=True, buckets=(8,))
        a.fit(it(), epochs=2)
        b.fit(it(), epochs=2)
        assert _leaves_equal(a.params, b.params)

    def test_lstm_seq_bucketing(self):
        """Time-axis padding (T=9 -> bucket 12) with generated masks is
        bit-identical to the unpadded run."""
        x = R.normal(size=(8, 9, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            R.integers(0, 3, (8, 9))].astype(np.float32)
        a = _mlp(seed=9, recurrent=True)
        b = _mlp(seed=9, recurrent=True, seq=(12,))
        a.fit(DataSet(x, y))
        b.fit(DataSet(x, y))
        assert _leaves_equal(a.params, b.params)

    def test_tbptt_remainder_bit_identity(self):
        xt = R.normal(size=(8, 11, 6)).astype(np.float32)
        yt = np.eye(3, dtype=np.float32)[
            R.integers(0, 3, (8, 11))].astype(np.float32)
        a = _mlp(seed=11, tbptt=4, recurrent=True)
        b = _mlp(seed=11, tbptt=4, recurrent=True, buckets=(8,), seq=(4,))
        a.fit(DataSet(xt, yt))
        b.fit(DataSet(xt, yt))
        assert _leaves_equal(a.params, b.params)
        assert float(a.score_value) == float(b.score_value)


# ---------------------------------------------------------------------------
# AOT warmup
# ---------------------------------------------------------------------------
class TestWarmup:
    def test_mln_warmup_zero_traces(self):
        x, y = _dense_data(n=21)
        w = get_watcher()
        net = _mlp(buckets=(8, 16))
        built = net.warmup()
        assert built == 4  # 2 buckets x (train step + forward)
        with w.scope() as s:
            net.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            net.output(x[:3])
            assert s.traces == 0

    def test_warmup_matches_jit_path_exactly(self):
        x, y = _dense_data(n=21)
        warmed = _mlp(buckets=(8, 16))
        warmed.warmup()
        plain = _mlp(buckets=(8, 16))
        warmed.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
        plain.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
        assert _leaves_equal(warmed.params, plain.params)

    def test_cg_warmup_zero_traces(self):
        x, y = _dense_data(n=19, f=10, c=4)
        w = get_watcher()
        g = _cg(buckets=(8, 16))
        built = g.warmup()
        assert built == 4
        with w.scope() as s:
            g.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            g.output(x[:5])
            assert s.traces == 0

    def test_warmup_explicit_shapes(self):
        net = _mlp(buckets=(8,))
        assert net.warmup(shapes=[(16, 12)], inference=False) == 1
        w = get_watcher()
        x, y = _dense_data(n=16)
        with w.scope() as s:
            net._fit_batch(x, y)
            assert s.traces_of("MultiLayerNetwork.train_step") == 0

    def test_warmup_export_store_round_trip(self, tmp_path):
        """The on-disk AOT lowering store: a fresh net's warmup LOADS the
        serialized module (0 traces) and its trajectory matches the plain
        jit path bit-for-bit."""
        d = str(tmp_path / "aot")
        x, y = _dense_data(n=21)
        first = _mlp(buckets=(8,))
        assert first.warmup(export_dir=d) == 2
        from deeplearning4j_tpu.util import AotStore

        assert AotStore(d).entries() == 2
        w = get_watcher()
        fresh = _mlp(buckets=(8,))
        with w.scope() as s:
            fresh.warmup(export_dir=d)   # deserialize: no re-trace
            fresh.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
            assert s.traces == 0
        plain = _mlp(buckets=(8,))
        plain.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
        assert _leaves_equal(fresh.params, plain.params)
        np.testing.assert_array_equal(np.asarray(fresh.output(x[:3])),
                                      np.asarray(plain.output(x[:3])))

    def test_export_store_key_invalidates_on_conf_change(self, tmp_path):
        """A different model conf must MISS the store (fresh export), never
        load a stale lowering."""
        d = str(tmp_path / "aot2")
        _mlp(buckets=(8,), seed=7).warmup(export_dir=d, inference=False)
        from deeplearning4j_tpu.util import AotStore

        assert AotStore(d).entries() == 1
        _mlp(buckets=(8,), seed=8).warmup(export_dir=d, inference=False)
        assert AotStore(d).entries() == 2  # different seed -> different key

    def test_warmup_requires_init_and_buckets(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(OutputLayer(n_in=4, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf)
        with pytest.raises(ValueError, match="init"):
            net.warmup()
        net.init()
        with pytest.raises(ValueError, match="batch_buckets"):
            net.warmup()  # no bucketing configured, no shapes given


# ---------------------------------------------------------------------------
# SameDiff cross-instance executable cache
# ---------------------------------------------------------------------------
class TestSameDiffExecCache:
    @staticmethod
    def _build_graph():
        from deeplearning4j_tpu.samediff import SameDiff

        sd = SameDiff()
        x = sd.placeholder("x", shape=(4, 3))
        w = sd.var("w", np.arange(12, dtype=np.float32).reshape(3, 4) / 10)
        h = sd.math.tanh(sd.linalg.mmul(x, w))
        out = sd.math.mul(h, h)
        return sd, out.name

    def test_fresh_reload_hits_exec_cache(self):
        watcher = get_watcher()
        feed = {"x": R.normal(size=(4, 3)).astype(np.float32)}
        sd1, out1 = self._build_graph()
        with watcher.scope() as s:
            r1 = sd1.output(feed, [out1])
            first = s.traces_of("SameDiff.output")
        assert first == 1
        sd2, out2 = self._build_graph()  # fresh in-process "reload"
        assert sd1.fingerprint() == sd2.fingerprint()
        with watcher.scope() as s:
            r2 = sd2.output(feed, [out2])
            assert s.traces_of("SameDiff.output") == 0  # exec-cache hit
        np.testing.assert_array_equal(r1[out1], r2[out2])

    def test_different_graph_misses(self):
        watcher = get_watcher()
        sd1, out1 = self._build_graph()
        feed = {"x": R.normal(size=(4, 3)).astype(np.float32)}
        sd1.output(feed, [out1])
        from deeplearning4j_tpu.samediff import SameDiff

        sd3 = SameDiff()
        x = sd3.placeholder("x", shape=(4, 3))
        w = sd3.var("w", np.arange(12, dtype=np.float32).reshape(3, 4) / 10)
        out3 = sd3.math.sin(sd3.linalg.mmul(x, w))  # different op
        assert sd3.fingerprint() != sd1.fingerprint()
        with watcher.scope() as s:
            sd3.output(feed, [out3.name])
            assert s.traces_of("SameDiff.output") == 1

    def test_mutation_invalidates_fingerprint(self):
        sd, out = self._build_graph()
        fp = sd.fingerprint()
        sd.math.add(sd.get_variable(out), sd.get_variable(out))
        assert sd.fingerprint() != fp


# ---------------------------------------------------------------------------
# Persistent on-disk compilation cache
# ---------------------------------------------------------------------------
class TestPersistentCache:
    def test_enable_disable_round_trip(self, tmp_path):
        from deeplearning4j_tpu.util import (cache_entries,
                                             disable_persistent_cache,
                                             enable_persistent_cache)

        d = str(tmp_path / "cc")
        try:
            got = enable_persistent_cache(d)
            assert got == os.path.abspath(d) and os.path.isdir(d)
            assert jax.config.jax_compilation_cache_dir == got

            @jax.jit
            def f(a):
                return a * 3 + 1

            f(np.ones(7, np.float32))
            assert cache_entries(d) >= 1
        finally:
            disable_persistent_cache()
        assert jax.config.jax_compilation_cache_dir is None

    @pytest.mark.slow
    def test_second_process_hits_cache(self, tmp_path):
        """Cross-process: a restarted process deserializes instead of
        recompiling (the cold-start win bench_recompile_overhead measures)."""
        child = (
            "import sys, json, jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from deeplearning4j_tpu.util import (enable_persistent_cache,"
            " get_watcher)\n"
            "enable_persistent_cache(sys.argv[1])\n"
            "import numpy as np\n"
            "w = get_watcher()\n"
            "f = jax.jit(lambda a: (a @ a.T).sum() * 2)\n"
            "f(np.ones((32, 32), np.float32))\n"
            "print(json.dumps(w.counts()))\n"
        )
        d = str(tmp_path / "cc2")

        def run():
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            out = subprocess.run([sys.executable, "-c", child, d], env=env,
                                 capture_output=True, text=True, timeout=300)
            assert out.returncode == 0, out.stderr[-2000:]
            return json.loads(out.stdout.strip().splitlines()[-1])

        cold, warm = run(), run()
        assert cold["persistent_cache_hits"] == 0
        assert warm["persistent_cache_hits"] > 0
        # jax logs a backend_compile event even on a cache hit; the honest
        # recompile count is compiles minus hits
        assert warm["uncached_compiles"] < cold["uncached_compiles"]


# ---------------------------------------------------------------------------
# Observability: watcher, listener, stats
# ---------------------------------------------------------------------------
class TestObservabilitySurface:
    def test_watcher_counts_and_summary(self):
        w = get_watcher()
        with w.scope() as s:
            f = jax.jit(lambda a: a + 1)
            f(np.ones(3, np.float32))
            assert s.backend_compiles >= 1
        counts = w.counts()
        assert {"traces", "backend_compiles", "persistent_cache_hits",
                "total_traces"} <= set(counts)
        assert "CompileWatcher" in w.summary()

    def test_recompile_listener_flags_new_shapes(self):
        from deeplearning4j_tpu.nn.listeners import RecompileListener

        logs = []
        net = _mlp()
        lst = RecompileListener(grace=1, log_fn=logs.append)
        net.set_listeners(lst)
        x, y = _dense_data(n=8)
        net.fit(x, y)   # iteration 1: inside grace, no event
        assert not lst.events
        x2, y2 = _dense_data(n=6)
        net.fit(x2, y2)  # new shape past grace: recompile event
        assert lst.events and lst.events[0][1] == "MultiLayerNetwork.train_step"
        assert logs and "RECOMPILE" in logs[0]

    def test_stats_listener_records_compile_group(self):
        from deeplearning4j_tpu.util import InMemoryStatsStorage, StatsListener

        store = InMemoryStatsStorage()
        net = _mlp()
        net.set_listeners(StatsListener(store, frequency=1,
                                        collect_histograms=False))
        x, y = _dense_data(n=8)
        net.fit(x, y)
        rec = store.records[-1]
        assert "compile" in rec
        assert rec["compile"]["total_traces"] >= 1


# ---------------------------------------------------------------------------
# Bucketed serving (ParallelInference)
# ---------------------------------------------------------------------------
class TestBucketedServing:
    def test_inference_bucketing_bounds_signatures(self):
        from deeplearning4j_tpu.parallel import ParallelInference, TrainingMesh

        net = _mlp(buckets=(8, 16))
        pi = ParallelInference(net, mesh=TrainingMesh(
            data=1, devices=jax.devices()[:1]))
        assert pi.bucketing is not None  # inherited from the model conf
        w = get_watcher()
        x, _ = _dense_data(n=16)
        with w.scope() as s:
            for n in (3, 5, 7, 8, 2, 6):
                out = pi.output(x[:n])
                assert out.shape == (n, 5)
            assert s.traces_of("MultiLayerNetwork.forward") <= 1

    def test_inference_warmup(self):
        from deeplearning4j_tpu.parallel import ParallelInference, TrainingMesh

        net = _mlp(buckets=(8, 16))
        pi = ParallelInference(net, mesh=TrainingMesh(
            data=1, devices=jax.devices()[:1]))
        assert pi.warmup() == 2
        w = get_watcher()
        x, _ = _dense_data(n=16)
        with w.scope() as s:
            pi.output(x[:5])
            pi.output(x[:13])
            assert s.traces_of("MultiLayerNetwork.forward") == 0

    def test_wrapper_warmup_preserves_model_state(self):
        from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh

        net = _mlp(buckets=(8,))
        before = jax.tree_util.tree_map(np.asarray, net.params)
        pw = ParallelWrapper(net, mesh=TrainingMesh(
            data=2, devices=jax.devices()[:2]))
        assert pw.warmup([8]) == 1
        assert _leaves_equal(before, net.params)
