"""Gradient checks over the op table — OpValidation/GradientCheckUtil parity.

Central fp64 finite differences vs jax.grad, across representative ops from
each differentiable family (SURVEY.md §4: "every layer type has a gradcheck";
here, every op family)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import ops
from deeplearning4j_tpu.autodiff import gradcheck


def _check(fn, *args, **kw):
    res = gradcheck.check_gradients(fn, args, **kw)
    assert res.passed, res
    return res


def test_gradcheck_catches_wrong_gradient():
    # sanity: harness must FAIL for a function with a lying custom gradient
    import jax

    @jax.custom_vjp
    def bad(x):
        return jnp.sum(x * x)

    bad.defvjp(lambda x: (jnp.sum(x * x), None), lambda _, g: (jnp.zeros(3),))
    res = gradcheck.check_gradients(bad, [jnp.array([1.0, 2.0, 3.0])])
    assert not res.passed


@pytest.mark.parametrize("name", ["exp", "tanh", "sigmoid", "softplus", "gelu", "swish", "mish", "erf"])
def test_transform_gradients(name, rng):
    x = jnp.asarray(rng.standard_normal((6,)))
    _check(lambda x: jnp.sum(ops.exec_op(name, x) ** 2), x)


@pytest.mark.parametrize("name", ["add", "multiply", "divide", "pow", "atan2"])
def test_pairwise_gradients(name, rng):
    x = jnp.asarray(np.abs(rng.standard_normal((5,))) + 0.5)
    y = jnp.asarray(np.abs(rng.standard_normal((5,))) + 0.5)
    _check(lambda x, y: jnp.sum(ops.exec_op(name, x, y)), x, y)


@pytest.mark.parametrize(
    "name,kw",
    [("sum", {}), ("mean", {}), ("norm2", {}), ("logsumexp", {}), ("max", {}), ("prod", {})],
)
def test_reduce_gradients(name, kw, rng):
    x = jnp.asarray(rng.standard_normal((4, 3)) + 2.0)
    _check(lambda x: ops.exec_op(name, x, **kw).sum() if name == "max" else jnp.sum(ops.exec_op(name, x, **kw)), x)


def test_matmul_gradient(rng):
    a = jnp.asarray(rng.standard_normal((3, 4)))
    b = jnp.asarray(rng.standard_normal((4, 2)))
    _check(lambda a, b: jnp.sum(ops.exec_op("matmul", a, b) ** 2), a, b)


def test_conv2d_gradient(rng):
    x = jnp.asarray(rng.standard_normal((1, 5, 5, 2)))
    w = jnp.asarray(rng.standard_normal((3, 3, 2, 3)))

    def f(x, w):
        return jnp.sum(ops.exec_op("conv2d", x, w, padding="VALID", preferred_element_type=None) ** 2)

    _check(f, x, w, max_rel_error=1e-4)


def test_maxpool_gradient(rng):
    x = jnp.asarray(rng.standard_normal((1, 4, 4, 2)))
    _check(lambda x: jnp.sum(ops.exec_op("maxpool2d", x, kernel=(2, 2)) ** 2), x)


def test_batchnorm_gradient(rng):
    x = jnp.asarray(rng.standard_normal((8, 3)))
    gamma = jnp.asarray(rng.standard_normal((3,)))
    beta = jnp.asarray(rng.standard_normal((3,)))

    def f(x, gamma, beta):
        out, _, _ = ops.exec_op(
            "batchnorm_train", x, gamma, beta, jnp.zeros(3), jnp.ones(3)
        )
        return jnp.sum(out**2)

    # eps=1e-6 hits fp64 cancellation noise on this function scale; 1e-4 converges
    _check(f, x, gamma, beta, eps=1e-4, max_rel_error=1e-4)


def test_layernorm_gradient(rng):
    x = jnp.asarray(rng.standard_normal((4, 6)))
    _check(lambda x: jnp.sum(ops.exec_op("layernorm", x) ** 3), x, eps=1e-4, max_rel_error=1e-4)


@pytest.mark.parametrize("loss", ["softmax_cross_entropy", "mse_loss", "huber_loss", "log_loss"])
def test_loss_gradients(loss, rng):
    logits = jnp.asarray(rng.standard_normal((4, 5)))
    if loss == "log_loss":
        preds = jnp.asarray(rng.uniform(0.1, 0.9, (4, 5)))
        labels = jnp.asarray(rng.integers(0, 2, (4, 5)).astype(np.float64))
        _check(lambda p: ops.exec_op(loss, p, labels), preds)
    else:
        labels = jnp.asarray(np.eye(5)[rng.integers(0, 5, 4)])
        _check(lambda lg: ops.exec_op(loss, lg, labels), logits)


def test_attention_gradient(rng):
    q = jnp.asarray(rng.standard_normal((1, 1, 3, 4)) * 0.5)
    k = jnp.asarray(rng.standard_normal((1, 1, 3, 4)) * 0.5)
    v = jnp.asarray(rng.standard_normal((1, 1, 3, 4)))

    def f(q, k, v):
        return jnp.sum(ops.exec_op("dot_product_attention", q, k, v) ** 2)

    _check(f, q, k, v, eps=1e-4, max_rel_error=1e-4)


def test_gather_gradient(rng):
    x = jnp.asarray(rng.standard_normal((5, 3)))
    idx = jnp.array([0, 2, 2, 4])
    _check(lambda x: jnp.sum(ops.exec_op("gather", x, idx) ** 2), x, argnums=0)


def test_model_gradcheck_pytree(rng):
    params = {
        "w1": jnp.asarray(rng.standard_normal((4, 8)) * 0.5),
        "b1": jnp.zeros(8),
        "w2": jnp.asarray(rng.standard_normal((8, 3)) * 0.5),
    }
    x = jnp.asarray(rng.standard_normal((2, 4)))
    y = jnp.asarray(np.eye(3)[[0, 2]])

    def loss_fn(p):
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        return ops.exec_op("softmax_cross_entropy", h @ p["w2"], y)

    res = gradcheck.check_model_gradients(loss_fn, params)
    assert res.passed, res
