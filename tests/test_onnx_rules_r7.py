"""Round-7 ONNX importer tail (VERDICT Missing #1): NonMaxSuppression wired
to the registry op, Hardmax added. Goldens: protomini-authored graphs against
the ONNX spec's own NMS example vectors and a numpy Hardmax reference (no
onnx package in the image — same strategy as the r5 rule tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.imports import import_onnx

from test_imports import (  # noqa: E402
    _onnx_attr_i,
    _onnx_input,
    _onnx_model,
    _onnx_node,
    _onnx_tensor,
)

R = np.random.default_rng(17)


def _run(model_bytes, feeds, outs):
    sd = import_onnx(model_bytes)
    res = sd.output(feeds, outs)
    return [np.asarray(res[o]) for o in outs]


class TestHardmax:
    @pytest.mark.parametrize("axis", [-1, 0, 1])
    def test_matches_numpy(self, axis):
        x = R.normal(size=(4, 5)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("Hardmax", ["x"], ["y"],
                              _onnx_attr_i("axis", axis))],
            initializers=[], inputs=[_onnx_input("x", (4, 5))], outputs=["y"])
        (y,) = _run(model, {"x": x}, ["y"])
        golden = np.zeros_like(x)
        idx = np.argmax(x, axis=axis)
        if axis % 2 == 0:
            golden[idx, np.arange(5)] = 1.0
        else:
            golden[np.arange(4), idx] = 1.0
        np.testing.assert_allclose(y, golden)

    def test_default_axis_rank3(self):
        x = R.normal(size=(2, 3, 4)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("Hardmax", ["x"], ["y"])],
            initializers=[], inputs=[_onnx_input("x", (2, 3, 4))],
            outputs=["y"])
        (y,) = _run(model, {"x": x}, ["y"])
        assert y.shape == x.shape
        np.testing.assert_allclose(y.sum(axis=-1), np.ones((2, 3)))
        np.testing.assert_allclose(np.argmax(y, axis=-1),
                                   np.argmax(x, axis=-1))


def _nms_model(num_boxes, num_classes=1, batch=1, center=0, with_score_th=False):
    inputs = ["boxes", "scores", "max_out", "iou_th"]
    inits = [
        _onnx_tensor("max_out", np.asarray([3], np.int64)),
        _onnx_tensor("iou_th", np.asarray([0.5], np.float32)),
    ]
    if with_score_th:
        inputs.append("score_th")
        inits.append(_onnx_tensor("score_th", np.asarray([0.4], np.float32)))
    return _onnx_model(
        nodes=[_onnx_node("NonMaxSuppression", inputs, ["sel"],
                          _onnx_attr_i("center_point_box", center))],
        initializers=inits,
        inputs=[_onnx_input("boxes", (batch, num_boxes, 4)),
                _onnx_input("scores", (batch, num_classes, num_boxes))],
        outputs=["sel"])


# the ONNX spec's own test vectors (onnx/backend/test/case/node/nonmaxsuppression.py)
_SPEC_BOXES = np.asarray([[
    [0.0, 0.0, 1.0, 1.0], [0.0, 0.1, 1.0, 1.1], [0.0, -0.1, 1.0, 0.9],
    [0.0, 10.0, 1.0, 11.0], [0.0, 10.1, 1.0, 11.1], [0.0, 100.0, 1.0, 101.0],
]], np.float32)
_SPEC_SCORES = np.asarray([[[0.9, 0.75, 0.6, 0.95, 0.5, 0.3]]], np.float32)


class TestNonMaxSuppression:
    def test_spec_suppress_by_iou(self):
        (sel,) = _run(_nms_model(6), {"boxes": _SPEC_BOXES,
                                      "scores": _SPEC_SCORES}, ["sel"])
        assert sel.shape == (3, 3)  # padded static variant: B*C*max_out rows
        np.testing.assert_array_equal(
            sel, np.asarray([[0, 0, 3], [0, 0, 0], [0, 0, 5]]))

    def test_spec_score_threshold(self):
        (sel,) = _run(_nms_model(6, with_score_th=True),
                      {"boxes": _SPEC_BOXES, "scores": _SPEC_SCORES}, ["sel"])
        # score_threshold 0.4 drops box 5 (0.3): third slot is -1 padding
        np.testing.assert_array_equal(
            sel, np.asarray([[0, 0, 3], [0, 0, 0], [-1, -1, -1]]))

    def test_center_point_box_and_flipped_corners(self):
        # same boxes expressed center-form must select identically
        corners = _SPEC_BOXES[0]
        centers = np.stack([
            (corners[:, 1] + corners[:, 3]) / 2,  # x_center
            (corners[:, 0] + corners[:, 2]) / 2,  # y_center
            corners[:, 3] - corners[:, 1],        # width
            corners[:, 0] - corners[:, 2],        # height (sign-free)
        ], axis=-1)[None].astype(np.float32)
        (sel_center,) = _run(
            _nms_model(6, center=1),
            {"boxes": np.abs(centers), "scores": _SPEC_SCORES}, ["sel"])
        # flipped diagonal corners ([y2,x2,y1,x1]) normalize to the same boxes
        flipped = _SPEC_BOXES[:, :, [2, 3, 0, 1]]
        (sel_flip,) = _run(_nms_model(6),
                           {"boxes": flipped, "scores": _SPEC_SCORES},
                           ["sel"])
        expected = np.asarray([[0, 0, 3], [0, 0, 0], [0, 0, 5]])
        np.testing.assert_array_equal(sel_center, expected)
        np.testing.assert_array_equal(sel_flip, expected)

    def test_two_classes_two_batches(self):
        boxes = np.concatenate([_SPEC_BOXES, _SPEC_BOXES])  # (2, 6, 4)
        scores = np.concatenate(
            [np.concatenate([_SPEC_SCORES, _SPEC_SCORES], axis=1)] * 2
        )  # (2, 2, 6)
        (sel,) = _run(_nms_model(6, num_classes=2, batch=2),
                      {"boxes": boxes, "scores": scores}, ["sel"])
        assert sel.shape == (2 * 2 * 3, 3)
        per = np.asarray([3, 0, 5])
        expected = np.concatenate([
            np.stack([np.full(3, b), np.full(3, c), per], axis=-1)
            for b in range(2) for c in range(2)])
        np.testing.assert_array_equal(sel, expected)


# ---------------------------------------------------------------------------
# BitShift (r13 WAIVED.md burn-down): elementwise integer shift, direction
# attribute LEFT/RIGHT, wired to the registry shift_left/shift_right ops.
# ---------------------------------------------------------------------------

from deeplearning4j_tpu.imports import protomini as pm  # noqa: E402
from test_imports import _onnx_tensor  # noqa: E402


def _onnx_attr_s(name, v):
    return pm.f_str(1, name) + pm.f_str(4, v) + pm.f_varint(20, 3)


class TestBitShift:
    def _model(self, x, y, direction):
        return _onnx_model(
            nodes=[_onnx_node("BitShift", ["x", "s"], ["y"],
                              _onnx_attr_s("direction", direction))],
            initializers=[_onnx_tensor("x", x), _onnx_tensor("s", y)],
            inputs=[], outputs=["y"])

    @pytest.mark.parametrize("dtype", [np.uint8, np.int32])
    def test_left(self, dtype):
        x = np.asarray([1, 2, 3, 7], dtype)
        s = np.asarray([1, 2, 0, 3], dtype)
        (y,) = _run(self._model(x, s, "LEFT"), {}, ["y"])
        np.testing.assert_array_equal(y, np.left_shift(x, s))

    @pytest.mark.parametrize("dtype", [np.uint8, np.int32])
    def test_right(self, dtype):
        x = np.asarray([16, 4, 1, 255 if np.dtype(dtype) == np.uint8
                        else 1024], dtype)
        s = np.asarray([1, 2, 1, 3], dtype)
        (y,) = _run(self._model(x, s, "RIGHT"), {}, ["y"])
        np.testing.assert_array_equal(y, np.right_shift(x, s))

    def test_broadcast_and_bad_direction(self):
        x = np.arange(6, dtype=np.int32).reshape(2, 3)
        s = np.asarray([1], np.int32)
        (y,) = _run(self._model(x, s, "LEFT"), {}, ["y"])
        np.testing.assert_array_equal(y, np.left_shift(x, 1))
        with pytest.raises(ValueError, match="direction"):
            _run(self._model(x, s, "UP"), {}, ["y"])


# ---------------------------------------------------------------------------
# MelWeightMatrix (r14 WAIVED.md burn-down): 5-scalar constant generator,
# folded at import time to the registry mel_weight_matrix op. Golden: an
# independent transliteration of the ONNX spec's reference pseudocode
# (onnx/backend/test/case/node/melweightmatrix.py semantics — no onnx
# package in the image, the r5 strategy).
# ---------------------------------------------------------------------------


def _mel_reference(num_mel_bins, dft_length, sample_rate, lower, upper):
    num_spectrogram_bins = dft_length // 2 + 1
    pts = np.arange(num_mel_bins + 2, dtype=np.float64)
    lo_mel = 2595.0 * np.log10(1.0 + lower / 700.0)
    hi_mel = 2595.0 * np.log10(1.0 + upper / 700.0)
    mels = pts * ((hi_mel - lo_mel) / pts.shape[0]) + lo_mel
    hz = 700.0 * (np.power(10.0, mels / 2595.0) - 1.0)
    bins = (((dft_length + 1) * hz) // sample_rate).astype(int)
    out = np.zeros((max(num_spectrogram_bins, bins.max() + 1),
                    num_mel_bins))
    for i in range(num_mel_bins):
        lo_b, c, hi_b = bins[i], bins[i + 1], bins[i + 2]
        if c == lo_b:
            out[c, i] = 1.0
        else:
            for j in range(lo_b, c + 1):
                out[j, i] = (j - lo_b) / float(c - lo_b)
        if hi_b > c:
            for j in range(c, hi_b):
                out[j, i] = (hi_b - j) / float(hi_b - c)
    return out[:num_spectrogram_bins].astype(np.float32)


class TestMelWeightMatrix:
    def _model(self, nmb, dft, sr, lo, hi, *attrs):
        return _onnx_model(
            nodes=[_onnx_node(
                "MelWeightMatrix",
                ["nmb", "dft", "sr", "lo", "hi"], ["y"], *attrs)],
            initializers=[
                _onnx_tensor("nmb", np.asarray([nmb], np.int64)),
                _onnx_tensor("dft", np.asarray([dft], np.int64)),
                _onnx_tensor("sr", np.asarray([sr], np.int64)),
                _onnx_tensor("lo", np.asarray([lo], np.float32)),
                _onnx_tensor("hi", np.asarray([hi], np.float32)),
            ],
            inputs=[], outputs=["y"])

    def test_spec_vector(self):
        # the ONNX test_melweightmatrix configuration
        nmb, dft, sr, lo, hi = 8, 16, 8192, 0.0, 8192.0
        (y,) = _run(self._model(nmb, dft, sr, lo, hi), {}, ["y"])
        assert y.shape == (dft // 2 + 1, nmb)
        np.testing.assert_allclose(y, _mel_reference(nmb, dft, sr, lo, hi),
                                   rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("cfg", [
        (5, 32, 16000, 20.0, 8000.0),
        (3, 8, 8192, 0.0, 4096.0),
        (10, 64, 22050, 300.0, 10000.0),
    ])
    def test_matches_reference_and_is_valid_filterbank(self, cfg):
        nmb, dft, sr, lo, hi = cfg
        (y,) = _run(self._model(nmb, dft, sr, lo, hi), {}, ["y"])
        ref = _mel_reference(nmb, dft, sr, lo, hi)
        np.testing.assert_allclose(y, ref, rtol=1e-6, atol=1e-7)
        assert y.shape == (dft // 2 + 1, nmb)
        assert (y >= 0.0).all() and (y <= 1.0).all()
        # every mel filter carries some mass
        assert (y.sum(axis=0) > 0.0).all()

    def test_output_datatype_attr(self):
        # output_datatype 11 = double (TensorProto enum). The registry op
        # preserves it exactly (host-side constant generator); the imported
        # graph's value passes through the backend, which truncates f64 to
        # f32 unless x64 is enabled — values must match either way.
        from deeplearning4j_tpu.ops.signal import mel_weight_matrix

        direct = mel_weight_matrix(4, 16, 8192, 0.0, 4096.0,
                                   dtype=np.float64)
        assert direct.dtype == np.float64
        (y,) = _run(self._model(4, 16, 8192, 0.0, 4096.0,
                                _onnx_attr_i("output_datatype", 11)),
                    {}, ["y"])
        assert y.dtype in (np.float32, np.float64)
        np.testing.assert_allclose(y, direct, rtol=1e-6, atol=1e-7)

    def test_registry_op_direct(self):
        from deeplearning4j_tpu import ops as dlops

        y = np.asarray(dlops.exec_op("mel_weight_matrix", 6, 32, 16000,
                                     0.0, 8000.0))
        np.testing.assert_allclose(
            y, _mel_reference(6, 32, 16000, 0.0, 8000.0),
            rtol=1e-6, atol=1e-7)
