"""Round-5 ONNX rule expansion tests: QDQ quantization, normalization
tail, spatial samplers, signal ops, losses, random family, const-foldable
dynamic ops.

Goldens: torch exports where the exporter emits the op (GridSample,
SoftmaxCrossEntropyLoss), protomini-authored graphs against numpy/torch
functional references everywhere else (same strategy as the Scan test —
no onnx package in the image, and torchvision is absent)."""

import io
import warnings

import jax
import numpy as np
import pytest
import torch

warnings.filterwarnings("ignore")

from deeplearning4j_tpu.imports import import_onnx  # noqa: E402

from test_imports import (  # noqa: E402
    _onnx_attr_f,
    _onnx_attr_i,
    _onnx_attr_ints,
    _onnx_input,
    _onnx_model,
    _onnx_node,
    _onnx_tensor,
)
from test_imports import _onnx_attr_s  # noqa: E402

R = np.random.default_rng(9)


def _export(model, args, input_names, output_names):
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda mb, co: mb
    try:
        buf = io.BytesIO()
        torch.onnx.export(model, args, buf, input_names=input_names,
                          output_names=output_names, dynamo=False)
        return buf.getvalue()
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


def _run(model_bytes, feeds, outs):
    sd = import_onnx(model_bytes)
    res = sd.output(feeds, outs)
    return [np.asarray(res[o]) for o in outs]


class TestGridSample:
    @pytest.mark.parametrize("align", [False, True])
    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    def test_torch_golden(self, mode, align):
        class G(torch.nn.Module):
            def forward(self, x, g):
                return torch.nn.functional.grid_sample(
                    x, g, mode=mode, padding_mode="zeros",
                    align_corners=align)

        x = torch.randn(2, 3, 5, 6)
        g = torch.rand(2, 4, 4, 2) * 2.2 - 1.1   # includes out-of-bounds
        data = _export(G().eval(), (x, g), ["x", "g"], ["y"])
        (y,) = _run(data, {"x": x.numpy(), "g": g.numpy()}, ["y"])
        with torch.no_grad():
            golden = G()(x, g).numpy()
        np.testing.assert_allclose(y, golden, atol=1e-5, rtol=1e-4)

    def test_border_padding(self):
        class G(torch.nn.Module):
            def forward(self, x, g):
                return torch.nn.functional.grid_sample(
                    x, g, padding_mode="border", align_corners=True)

        x = torch.randn(1, 2, 4, 4)
        g = torch.rand(1, 3, 3, 2) * 3.0 - 1.5
        data = _export(G().eval(), (x, g), ["x", "g"], ["y"])
        (y,) = _run(data, {"x": x.numpy(), "g": g.numpy()}, ["y"])
        with torch.no_grad():
            golden = G()(x, g).numpy()
        np.testing.assert_allclose(y, golden, atol=1e-5, rtol=1e-4)


class TestQuantization:
    def test_qdq_roundtrip_per_tensor(self):
        x = R.normal(size=(2, 8)).astype(np.float32) * 3
        scale, zp = np.float32(0.05), np.uint8(128)
        model = _onnx_model(
            nodes=[
                _onnx_node("QuantizeLinear", ["x", "s", "z"], ["q"]),
                _onnx_node("DequantizeLinear", ["q", "s", "z"], ["y"]),
            ],
            initializers=[_onnx_tensor("s", scale.reshape(())),
                          _onnx_tensor("z", zp.reshape(()))],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["q", "y"],
        )
        q, y = _run(model, {"x": x}, ["q", "y"])
        ref_q = np.clip(np.round(x / scale) + zp, 0, 255).astype(np.uint8)
        np.testing.assert_array_equal(q, ref_q)
        np.testing.assert_allclose(
            y, (ref_q.astype(np.float32) - zp) * scale, atol=1e-6)

    def test_per_axis_dequantize(self):
        q = R.integers(0, 255, size=(3, 4)).astype(np.uint8)
        scale = np.asarray([0.1, 0.2, 0.3], np.float32)
        zp = np.asarray([0, 10, 20], np.uint8)
        model = _onnx_model(
            nodes=[_onnx_node("DequantizeLinear", ["q", "s", "z"], ["y"],
                              _onnx_attr_i("axis", 0))],
            initializers=[_onnx_tensor("s", scale), _onnx_tensor("z", zp)],
            inputs=[_onnx_input("q", q.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"q": q}, ["y"])
        ref = (q.astype(np.float32) - zp[:, None].astype(np.float32)) \
            * scale[:, None]
        np.testing.assert_allclose(y, ref, atol=1e-6)

    def test_dynamic_quantize(self):
        x = R.normal(size=(12,)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("DynamicQuantizeLinear", ["x"],
                              ["y", "scale", "zp"])],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y", "scale", "zp"],
        )
        y, scale, zp = _run(model, {"x": x}, ["y", "scale", "zp"])
        rmin = min(0.0, float(x.min()))
        rmax = max(0.0, float(x.max()))
        ref_scale = (rmax - rmin) / 255.0
        ref_zp = np.clip(round(-rmin / ref_scale), 0, 255)
        np.testing.assert_allclose(float(scale), ref_scale, rtol=1e-5)
        assert int(zp) == int(ref_zp)
        ref_y = np.clip(np.round(x / ref_scale) + ref_zp, 0,
                        255).astype(np.uint8)
        np.testing.assert_array_equal(y, ref_y)


class TestNormalizationTail:
    def test_group_norm_vs_torch(self):
        x = R.normal(size=(2, 6, 4, 4)).astype(np.float32)
        w = R.normal(size=(6,)).astype(np.float32)
        b = R.normal(size=(6,)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("GroupNormalization", ["x", "w", "b"], ["y"],
                              _onnx_attr_i("num_groups", 3),
                              _onnx_attr_f("epsilon", 1e-5))],
            initializers=[_onnx_tensor("w", w), _onnx_tensor("b", b)],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        with torch.no_grad():
            golden = torch.nn.functional.group_norm(
                torch.from_numpy(x), 3, torch.from_numpy(w),
                torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(y, golden, atol=1e-5, rtol=1e-4)

    def test_mvn(self):
        x = R.normal(size=(2, 3, 4, 4)).astype(np.float32) * 5 + 2
        model = _onnx_model(
            nodes=[_onnx_node("MeanVarianceNormalization", ["x"], ["y"])],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        mean = x.mean(axis=(0, 2, 3), keepdims=True)
        var = x.var(axis=(0, 2, 3), keepdims=True)
        np.testing.assert_allclose(y, (x - mean) / np.sqrt(var + 1e-9),
                                   atol=1e-5, rtol=1e-4)


class TestScatterPool:
    def test_scatter_elements_reductions(self):
        x = np.zeros((3, 4), np.float32)
        idx = np.asarray([[0, 1], [2, 0]], np.int64)
        upd = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
        for red, ref_fn in [
            ("none", lambda: _scatter_ref(x, idx, upd, "none")),
            ("add", lambda: _scatter_ref(x, idx, upd, "add")),
        ]:
            attrs = [_onnx_attr_i("axis", 1)]
            if red != "none":
                attrs.append(_onnx_attr_s("reduction", red))
            model = _onnx_model(
                nodes=[_onnx_node("ScatterElements", ["x", "i", "u"],
                                  ["y"], *attrs)],
                initializers=[_onnx_tensor("i", idx),
                              _onnx_tensor("u", upd)],
                inputs=[_onnx_input("x", x.shape)],
                outputs=["y"],
            )
            (y,) = _run(model, {"x": x}, ["y"])
            np.testing.assert_allclose(y, ref_fn())

    def test_lp_pool_and_global(self):
        x = R.normal(size=(1, 2, 4, 4)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("LpPool", ["x"], ["y"],
                              _onnx_attr_ints("kernel_shape", [2, 2]),
                              _onnx_attr_ints("strides", [2, 2]),
                              _onnx_attr_i("p", 2))],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = np.zeros((1, 2, 2, 2), np.float32)
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    blk = x[0, c, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
                    ref[0, c, i, j] = np.sqrt((blk ** 2).sum())
        np.testing.assert_allclose(y, ref, rtol=1e-5)

        gmodel = _onnx_model(
            nodes=[_onnx_node("GlobalLpPool", ["x"], ["y"],
                              _onnx_attr_i("p", 2))],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (gy,) = _run(gmodel, {"x": x}, ["y"])
        gref = np.sqrt((x ** 2).sum(axis=(2, 3), keepdims=True))
        np.testing.assert_allclose(gy, gref, rtol=1e-5)

    def test_global_pools_rank5(self):
        """ADVICE r5: the Global*Pool rules hardcoded spatial axes (2, 3), so
        a rank-5 (N,C,D,H,W) input silently pooled only two of its three
        spatial dims; axes now derive from input rank."""
        x = R.normal(size=(2, 3, 2, 4, 4)).astype(np.float32)
        refs = {
            "GlobalLpPool": np.sqrt((x ** 2).sum(axis=(2, 3, 4),
                                                 keepdims=True)),
            "GlobalAveragePool": x.mean(axis=(2, 3, 4), keepdims=True),
            "GlobalMaxPool": x.max(axis=(2, 3, 4), keepdims=True),
        }
        for op_t, ref in refs.items():
            attrs = [_onnx_attr_i("p", 2)] if op_t == "GlobalLpPool" else []
            model = _onnx_model(
                nodes=[_onnx_node(op_t, ["x"], ["y"], *attrs)],
                initializers=[],
                inputs=[_onnx_input("x", x.shape)],
                outputs=["y"],
            )
            (y,) = _run(model, {"x": x}, ["y"])
            assert y.shape == (2, 3, 1, 1, 1), op_t
            np.testing.assert_allclose(y, ref, rtol=1e-5, err_msg=op_t)

    def test_upsample_nearest(self):
        x = R.normal(size=(1, 2, 3, 3)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("Upsample", ["x", "s"], ["y"],
                              _onnx_attr_s("mode", "nearest"))],
            initializers=[_onnx_tensor(
                "s", np.asarray([1.0, 1.0, 2.0, 2.0], np.float32))],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = x.repeat(2, axis=2).repeat(2, axis=3)
        np.testing.assert_allclose(y, ref)

    def test_max_unpool_default_strides_are_one(self):
        # review regression: missing strides attr = 1 per axis by spec,
        # so a (1,1,2,2) pooled input unpools to (1,1,3,3), not (1,1,4,4)
        vals = np.ones((1, 1, 2, 2), np.float32)
        idx = np.asarray([[[[0, 2], [6, 8]]]], np.int64)
        model = _onnx_model(
            nodes=[_onnx_node("MaxUnpool", ["v", "i"], ["y"],
                              _onnx_attr_ints("kernel_shape", [2, 2]))],
            initializers=[_onnx_tensor("i", idx)],
            inputs=[_onnx_input("v", vals.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"v": vals}, ["y"])
        assert y.shape == (1, 1, 3, 3)
        assert y.reshape(-1)[[0, 2, 6, 8]].sum() == 4.0

    def test_max_unpool(self):
        # MaxPool 2x2 on a 4x4, then MaxUnpool restores positions
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        vals = x[:, :, 1::2, 1::2]
        idx = np.asarray([[[[5, 7], [13, 15]]]], np.int64)
        model = _onnx_model(
            nodes=[_onnx_node("MaxUnpool", ["v", "i"], ["y"],
                              _onnx_attr_ints("kernel_shape", [2, 2]),
                              _onnx_attr_ints("strides", [2, 2]))],
            initializers=[_onnx_tensor("i", idx)],
            inputs=[_onnx_input("v", vals.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"v": vals}, ["y"])
        ref = np.zeros_like(x)
        ref.reshape(-1)[idx.reshape(-1)] = vals.reshape(-1)
        np.testing.assert_allclose(y, ref)


def _scatter_ref(x, idx, upd, red):
    out = x.copy()
    for r in range(idx.shape[0]):
        for c in range(idx.shape[1]):
            if red == "add":
                out[r, idx[r, c]] += upd[r, c]
            else:
                out[r, idx[r, c]] = upd[r, c]
    return out


class TestRoiAlign:
    def test_vs_numpy_reference(self):
        x = R.normal(size=(1, 2, 8, 8)).astype(np.float32)
        rois = np.asarray([[1.0, 1.0, 6.0, 6.0], [0.0, 0.0, 7.0, 3.0]],
                          np.float32)
        bidx = np.zeros((2,), np.int64)
        model = _onnx_model(
            nodes=[_onnx_node(
                "RoiAlign", ["x", "r", "b"], ["y"],
                _onnx_attr_i("output_height", 2),
                _onnx_attr_i("output_width", 2),
                _onnx_attr_i("sampling_ratio", 2),
                _onnx_attr_f("spatial_scale", 1.0),
                _onnx_attr_s("coordinate_transformation_mode",
                             "half_pixel"))],
            initializers=[_onnx_tensor("r", rois),
                          _onnx_tensor("b", bidx)],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = _roi_align_ref(x, rois, bidx, (2, 2), 2, 1.0, True)
        np.testing.assert_allclose(y, ref, atol=1e-5, rtol=1e-4)


def _bilinear_ref(img, py, px):
    c, h, w = img.shape
    y0, x0 = int(np.floor(py)), int(np.floor(px))
    wy, wx = py - y0, px - x0
    out = np.zeros(c, img.dtype)
    for dy in (0, 1):
        for dx in (0, 1):
            yy = min(max(y0 + dy, 0), h - 1)
            xx = min(max(x0 + dx, 0), w - 1)
            wgt = (wy if dy else 1 - wy) * (wx if dx else 1 - wx)
            out += img[:, yy, xx] * wgt
    return out


def _roi_align_ref(x, rois, bidx, out_size, ratio, scale, aligned):
    oh, ow = out_size
    off = 0.5 if aligned else 0.0
    k = rois.shape[0]
    c = x.shape[1]
    out = np.zeros((k, c, oh, ow), np.float32)
    for r in range(k):
        img = x[int(bidx[r])]
        x1, y1, x2, y2 = rois[r] * scale - off
        bh, bw = (y2 - y1) / oh, (x2 - x1) / ow
        for i in range(oh):
            for j in range(ow):
                acc = np.zeros(c, np.float32)
                for si in range(ratio):
                    for sj in range(ratio):
                        py = y1 + bh * (i + (si + 0.5) / ratio)
                        px = x1 + bw * (j + (sj + 0.5) / ratio)
                        acc += _bilinear_ref(img, py, px)
                out[r, :, i, j] = acc / (ratio * ratio)
    return out


class TestSignal:
    def test_windows(self):
        for op_t, tfn in [("HannWindow", torch.hann_window),
                          ("HammingWindow", None),
                          ("BlackmanWindow", torch.blackman_window)]:
            model = _onnx_model(
                nodes=[_onnx_node(op_t, ["n"], ["w"])],
                initializers=[_onnx_tensor("n",
                                           np.asarray(16, np.int64))],
                inputs=[],
                outputs=["w"],
            )
            (w,) = _run(model, {}, ["w"])
            assert w.shape == (16,)
            if tfn is not None:
                np.testing.assert_allclose(
                    w, tfn(16, periodic=True).numpy(), atol=1e-5)
            else:
                # ONNX Hamming uses 25/46 coefficients
                k = np.arange(16)
                ref = 25 / 46 - (21 / 46) * np.cos(2 * np.pi * k / 16)
                np.testing.assert_allclose(w, ref, atol=1e-6)

    def test_dft_real_onesided_vs_numpy(self):
        x = R.normal(size=(2, 16, 1)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("DFT", ["x"], ["y"],
                              _onnx_attr_i("onesided", 1),
                              _onnx_attr_i("axis", 1))],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = np.fft.rfft(x[..., 0], axis=1)
        np.testing.assert_allclose(y[..., 0], ref.real, atol=1e-4)
        np.testing.assert_allclose(y[..., 1], ref.imag, atol=1e-4)

    def test_stft_vs_numpy(self):
        sig = R.normal(size=(1, 32)).astype(np.float32)
        win = np.hanning(8).astype(np.float32)  # symmetric window, any is fine
        model = _onnx_model(
            nodes=[_onnx_node("STFT", ["x", "st", "w"], ["y"],
                              _onnx_attr_i("onesided", 1))],
            initializers=[_onnx_tensor("st", np.asarray(4, np.int64)),
                          _onnx_tensor("w", win)],
            inputs=[_onnx_input("x", sig.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": sig}, ["y"])
        frames = np.stack([sig[0, i * 4:i * 4 + 8] * win
                           for i in range(7)])
        ref = np.fft.rfft(frames, axis=-1)
        np.testing.assert_allclose(y[0, ..., 0], ref.real, atol=1e-4)
        np.testing.assert_allclose(y[0, ..., 1], ref.imag, atol=1e-4)


class TestLosses:
    def test_softmax_cross_entropy_loss_torch_export(self):
        class M(torch.nn.Module):
            def forward(self, x, t):
                return torch.nn.functional.cross_entropy(x, t)

        x = torch.randn(4, 5)
        t = torch.tensor([0, 2, 4, 1])
        data = _export(M().eval(), (x, t), ["x", "t"], ["loss"])
        (loss,) = _run(data, {"x": x.numpy(), "t": t.numpy()}, ["loss"])
        np.testing.assert_allclose(float(loss), float(M()(x, t)),
                                   rtol=1e-5)

    def test_nll_loss_weighted_mean(self):
        lp = np.log(np.full((3, 4), 0.25, np.float32))
        target = np.asarray([0, 1, 2], np.int64)
        w = np.asarray([1.0, 2.0, 0.5, 1.0], np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("NegativeLogLikelihoodLoss",
                              ["lp", "t", "w"], ["y"],
                              _onnx_attr_s("reduction", "mean"))],
            initializers=[_onnx_tensor("t", target),
                          _onnx_tensor("w", w)],
            inputs=[_onnx_input("lp", lp.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"lp": lp}, ["y"])
        per = -lp[np.arange(3), target] * w[target]
        np.testing.assert_allclose(float(y), per.sum() / w[target].sum(),
                                   rtol=1e-5)


class TestRandomFamily:
    def test_random_normal_stats_and_determinism(self):
        model = _onnx_model(
            nodes=[_onnx_node("RandomNormal", [], ["y"],
                              _onnx_attr_ints("shape", [2000]),
                              _onnx_attr_f("mean", 3.0),
                              _onnx_attr_f("scale", 0.5))],
            initializers=[],
            inputs=[],
            outputs=["y"],
        )
        (a,) = _run(model, {}, ["y"])
        (b,) = _run(model, {}, ["y"])
        np.testing.assert_array_equal(a, b)  # seeded: deterministic
        assert abs(a.mean() - 3.0) < 0.1
        assert abs(a.std() - 0.5) < 0.05

    def test_random_uniform_range(self):
        model = _onnx_model(
            nodes=[_onnx_node("RandomUniform", [], ["y"],
                              _onnx_attr_ints("shape", [500]),
                              _onnx_attr_f("low", -2.0),
                              _onnx_attr_f("high", -1.0))],
            initializers=[],
            inputs=[],
            outputs=["y"],
        )
        (y,) = _run(model, {}, ["y"])
        assert y.min() >= -2.0 and y.max() <= -1.0

    def test_bernoulli_and_multinomial(self):
        p = np.full((400,), 0.25, np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("Bernoulli", ["p"], ["y"])],
            initializers=[_onnx_tensor("p", p)],
            inputs=[],
            outputs=["y"],
        )
        (y,) = _run(model, {}, ["y"])
        assert set(np.unique(y)) <= {0.0, 1.0}
        assert 0.1 < y.mean() < 0.45
        logits = np.log(np.asarray([[0.01, 0.01, 0.98]], np.float32))
        mmodel = _onnx_model(
            nodes=[_onnx_node("Multinomial", ["l"], ["s"],
                              _onnx_attr_i("sample_size", 64))],
            initializers=[_onnx_tensor("l", logits)],
            inputs=[],
            outputs=["s"],
        )
        (s,) = _run(mmodel, {}, ["s"])
        assert s.shape == (1, 64)
        assert (s == 2).mean() > 0.8


class TestConstFoldableDynamics:
    def test_compress_const_condition(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        cond = np.asarray([True, False, True])
        model = _onnx_model(
            nodes=[_onnx_node("Compress", ["x", "c"], ["y"],
                              _onnx_attr_i("axis", 0))],
            initializers=[_onnx_tensor("c", cond)],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        np.testing.assert_allclose(y, x[[0, 2]])

    def test_nonzero_and_unique_const_fold(self):
        v = np.asarray([[1, 0, 2], [0, 3, 0]], np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("NonZero", ["v"], ["y"])],
            initializers=[_onnx_tensor("v", v)],
            inputs=[],
            outputs=["y"],
        )
        (y,) = _run(model, {}, ["y"])
        np.testing.assert_array_equal(y, np.stack(np.nonzero(v)))

        u = np.asarray([3, 1, 3, 2, 1], np.float32)
        umodel = _onnx_model(
            nodes=[_onnx_node("Unique", ["u"], ["vals", "idx", "inv",
                                               "counts"],
                              _onnx_attr_i("sorted", 0))],
            initializers=[_onnx_tensor("u", u)],
            inputs=[],
            outputs=["vals", "inv", "counts"],
        )
        vals, inv, counts = _run(umodel, {}, ["vals", "inv", "counts"])
        np.testing.assert_allclose(vals, [3, 1, 2])  # first-occurrence order
        np.testing.assert_array_equal(inv, [0, 1, 0, 2, 1])
        np.testing.assert_array_equal(counts, [2, 2, 1])

    def test_nonzero_runtime_input_rejected(self):
        model = _onnx_model(
            nodes=[_onnx_node("NonZero", ["x"], ["y"])],
            initializers=[],
            inputs=[_onnx_input("x", (3,))],
            outputs=["y"],
        )
        with pytest.raises(NotImplementedError):
            import_onnx(model)


class TestReviewRegressions:
    """Round-5 review findings, each pinned by a test."""

    def test_two_random_nodes_draw_independent_streams(self):
        model = _onnx_model(
            nodes=[
                _onnx_node("RandomNormal", [], ["a"],
                           _onnx_attr_ints("shape", [64])),
                _onnx_node("RandomNormal", [], ["b"],
                           _onnx_attr_ints("shape", [64])),
            ],
            initializers=[],
            inputs=[],
            outputs=["a", "b"],
        )
        a, b = _run(model, {}, ["a", "b"])
        assert not np.allclose(a, b), "same-type random nodes correlated"

    def test_pool_default_strides_are_one(self):
        # spec: missing strides = 1 per axis (NOT kernel_shape)
        x = R.normal(size=(1, 1, 4, 4)).astype(np.float32)
        for op_t in ("MaxPool", "LpPool"):
            model = _onnx_model(
                nodes=[_onnx_node(op_t, ["x"], ["y"],
                                  _onnx_attr_ints("kernel_shape", [2, 2]))],
                initializers=[],
                inputs=[_onnx_input("x", x.shape)],
                outputs=["y"],
            )
            (y,) = _run(model, {"x": x}, ["y"])
            assert y.shape == (1, 1, 3, 3), (op_t, y.shape)
        ref = np.zeros((1, 1, 3, 3), np.float32)
        for i in range(3):
            for j in range(3):
                ref[0, 0, i, j] = x[0, 0, i:i + 2, j:j + 2].max()
        model = _onnx_model(
            nodes=[_onnx_node("MaxPool", ["x"], ["y"],
                              _onnx_attr_ints("kernel_shape", [2, 2]))],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        np.testing.assert_allclose(y, ref)

    def test_upsample_fractional_scale_rejected(self):
        model = _onnx_model(
            nodes=[_onnx_node("Upsample", ["x", "s"], ["y"],
                              _onnx_attr_s("mode", "nearest"))],
            initializers=[_onnx_tensor(
                "s", np.asarray([1.0, 1.0, 1.5, 1.5], np.float32))],
            inputs=[_onnx_input("x", (1, 1, 4, 4))],
            outputs=["y"],
        )
        with pytest.raises(NotImplementedError, match="non-integer"):
            import_onnx(model)

    def test_dft_negative_axis(self):
        x = R.normal(size=(2, 16, 1)).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("DFT", ["x"], ["y"],
                              _onnx_attr_i("onesided", 1),
                              _onnx_attr_i("axis", -2))],
            initializers=[],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = np.fft.rfft(x[..., 0], axis=1)
        np.testing.assert_allclose(y[..., 0], ref.real, atol=1e-4)

    def test_roi_align_legacy_no_ctm_attr(self):
        # pre-opset-16 node (no coordinate_transformation_mode): legacy
        # output_half_pixel semantics, i.e. NO -0.5 offset
        x = R.normal(size=(1, 1, 8, 8)).astype(np.float32)
        rois = np.asarray([[1.0, 1.0, 5.0, 5.0]], np.float32)
        bidx = np.zeros((1,), np.int64)
        model = _onnx_model(
            nodes=[_onnx_node(
                "RoiAlign", ["x", "r", "b"], ["y"],
                _onnx_attr_i("output_height", 2),
                _onnx_attr_i("output_width", 2),
                _onnx_attr_i("sampling_ratio", 2))],
            initializers=[_onnx_tensor("r", rois),
                          _onnx_tensor("b", bidx)],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = _roi_align_ref(x, rois, bidx, (2, 2), 2, 1.0, False)
        np.testing.assert_allclose(y, ref, atol=1e-5, rtol=1e-4)


class TestCenterCropPad:
    def test_crop_and_pad(self):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        model = _onnx_model(
            nodes=[_onnx_node("CenterCropPad", ["x", "t"], ["y"])],
            initializers=[_onnx_tensor(
                "t", np.asarray([2, 8], np.int64))],
            inputs=[_onnx_input("x", x.shape)],
            outputs=["y"],
        )
        (y,) = _run(model, {"x": x}, ["y"])
        ref = np.zeros((2, 8), np.float32)
        ref[:, 1:7] = x[1:3]
        np.testing.assert_allclose(y, ref)
