"""ModelSerializer / CheckpointListener / EarlyStopping / normalizer tests —
parity with the reference's ModelSerializerTest, CheckpointListener tests and
EarlyStoppingTests (deeplearning4j-core; SURVEY.md §5.4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.data.normalizers import (
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    normalizer_from_dict,
)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    TerminationReason,
)
from deeplearning4j_tpu.nn import (
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.listeners import CheckpointListener
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.util import ModelSerializer


def _net(seed=42):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Adam(0.01))
        .list()
        .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
        .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent", activation="softmax"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(rng, n=64):
    xs = rng.standard_normal((n, 4)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return xs, ys


def test_save_restore_exact_outputs(tmp_path, rng):
    net = _net()
    xs, ys = _data(rng)
    net.fit(xs, ys, epochs=3)
    path = str(tmp_path / "model.zip")
    ModelSerializer.write_model(net, path)

    restored = ModelSerializer.restore_multi_layer_network(path)
    np.testing.assert_array_equal(
        np.asarray(net.output(xs)), np.asarray(restored.output(xs))
    )
    assert restored.iteration == net.iteration
    assert restored.epoch == net.epoch


def test_resume_training_bit_exact(tmp_path, rng):
    """Save mid-training, resume, and compare against uninterrupted run —
    params must match exactly (updater state + RNG key round-trip)."""
    xs, ys = _data(rng)
    a = _net()
    a.fit(xs, ys, epochs=2)
    path = str(tmp_path / "mid.zip")
    ModelSerializer.write_model(a, path)
    a.fit(xs, ys, epochs=2)  # uninterrupted continuation

    b = ModelSerializer.restore_multi_layer_network(path)
    b.fit(xs, ys, epochs=2)  # resumed continuation

    for pa, pb in zip(a.params, b.params):
        for k in pa:
            np.testing.assert_allclose(
                np.asarray(pa[k]), np.asarray(pb[k]), rtol=1e-6, atol=1e-7
            )


def test_restore_without_updater_state(tmp_path, rng):
    net = _net()
    xs, ys = _data(rng)
    net.fit(xs, ys, epochs=1)
    path = str(tmp_path / "no_upd.zip")
    ModelSerializer.write_model(net, path, save_updater=False)
    restored = ModelSerializer.restore_multi_layer_network(path, load_updater=False)
    np.testing.assert_array_equal(
        np.asarray(net.output(xs)), np.asarray(restored.output(xs))
    )


def test_wrong_type_raises(tmp_path, rng):
    net = _net()
    path = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, path)
    with pytest.raises(ValueError, match="MultiLayerNetwork"):
        ModelSerializer.restore_computation_graph(path)


def test_normalizer_rides_in_archive(tmp_path, rng):
    net = _net()
    xs, ys = _data(rng)
    norm = NormalizerStandardize().fit(DataSet(xs, ys))
    path = str(tmp_path / "with_norm.zip")
    ModelSerializer.write_model(net, path, normalizer=norm)
    restored_norm = ModelSerializer.restore_normalizer_from_file(path)
    np.testing.assert_allclose(restored_norm.mean, norm.mean)
    np.testing.assert_allclose(restored_norm.std, norm.std)


def test_checkpoint_listener_keep_last(tmp_path, rng):
    import os

    net = _net()
    xs, ys = _data(rng)
    ckpt = CheckpointListener(
        str(tmp_path / "ckpts"), save_every_n_iterations=2, keep_last=2
    )
    net.set_listeners(ckpt)
    net.fit(ArrayDataSetIterator(xs, ys, batch=8), epochs=2)
    assert len(ckpt.saved) == 2
    assert all(os.path.exists(p) for p in ckpt.saved)
    # restorable
    restored = ModelSerializer.restore_model(ckpt.last_checkpoint())
    assert restored.output(xs).shape == (64, 3)


# ------------------------------------------------------------- normalizers
def test_standardize_roundtrip(rng):
    xs, ys = _data(rng, n=256)
    norm = NormalizerStandardize().fit(DataSet(xs, ys))
    ds = DataSet(xs.copy(), ys)
    norm.transform(ds)
    assert abs(ds.features.mean()) < 0.05
    assert abs(ds.features.std() - 1.0) < 0.05
    norm.revert(ds)
    np.testing.assert_allclose(ds.features, xs, rtol=1e-4, atol=1e-5)


def test_minmax_and_image_scaler(rng):
    xs = rng.uniform(-5, 9, (100, 6)).astype(np.float32)
    norm = NormalizerMinMaxScaler().fit(DataSet(xs, xs))
    out = norm.normalize(xs)
    assert out.min() >= -1e-6 and out.max() <= 1 + 1e-6
    np.testing.assert_allclose(norm.denormalize(out), xs, rtol=1e-4, atol=1e-4)

    img = (rng.uniform(0, 255, (4, 8, 8, 3))).astype(np.float32)
    sc = ImagePreProcessingScaler()
    np.testing.assert_allclose(sc.normalize(img), img / 255.0, rtol=1e-6)

    for n in (norm, sc, NormalizerStandardize().fit(DataSet(xs, xs))):
        back = normalizer_from_dict(n.to_dict())
        np.testing.assert_allclose(back.normalize(xs), n.normalize(xs), rtol=1e-5)


# ----------------------------------------------------------- early stopping
def test_early_stopping_max_epochs(rng):
    xs, ys = _data(rng, n=128)
    it = ArrayDataSetIterator(xs, ys, batch=32)
    val = ArrayDataSetIterator(xs, ys, batch=64)
    esc = (
        EarlyStoppingConfiguration.builder()
        .score_calculator(DataSetLossCalculator(val))
        .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
        .iteration_termination_conditions(InvalidScoreIterationTerminationCondition())
        .build()
    )
    result = EarlyStoppingTrainer(esc, _net(), it).fit()
    assert result.termination_reason == TerminationReason.EpochTerminationCondition
    assert result.total_epochs == 3
    assert result.best_model is not None
    assert result.best_model_score < 2.0


def test_early_stopping_score_improvement_patience(rng):
    xs, ys = _data(rng, n=128)
    it = ArrayDataSetIterator(xs, ys, batch=32)
    val = ArrayDataSetIterator(xs, ys, batch=64)
    esc = (
        EarlyStoppingConfiguration.builder()
        .score_calculator(DataSetLossCalculator(val))
        .epoch_termination_conditions(
            MaxEpochsTerminationCondition(50),
            ScoreImprovementEpochTerminationCondition(2, min_improvement=10.0),
        )
        .build()
    )
    result = EarlyStoppingTrainer(esc, _net(), it).fit()
    # 10.0 improvement per epoch is unattainable → patience trips after 3 epochs
    assert result.total_epochs <= 4
    assert result.termination_details == "ScoreImprovementEpochTerminationCondition"


def test_sharded_checkpointer_roundtrip(tmp_path, rng):
    from deeplearning4j_tpu.nn import (
        InputType, MultiLayerNetwork, NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import ShardedCheckpointer

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    xs = rng.standard_normal((16, 4)).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    net.fit(xs, ys, epochs=3)

    ckpt = ShardedCheckpointer(str(tmp_path / "ck"), keep=2)
    ckpt.save(net.iteration, net)
    net.fit(xs, ys, epochs=2)
    ckpt.save(net.iteration, net)
    assert len(ckpt.all_steps()) == 2
    assert ckpt.latest_step() == net.iteration

    # restore the earlier step into a fresh net: params + iteration round-trip
    net2 = MultiLayerNetwork(conf).init()
    ckpt.restore(net2, step=ckpt.all_steps()[0])
    assert net2.iteration == ckpt.all_steps()[0]
    import jax.numpy as jnp

    # Adam moments restored: one more identical fit step matches exactly
    net3 = MultiLayerNetwork(conf).init()
    ckpt.restore(net3, step=ckpt.all_steps()[0])
    net2._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    net3._fit_batch(jnp.asarray(xs), jnp.asarray(ys))
    np.testing.assert_allclose(np.asarray(net2.params[0]["W"]),
                               np.asarray(net3.params[0]["W"]), atol=1e-7)
    ckpt.close()


def test_fault_tolerant_trainer_recovers(tmp_path, rng):
    from deeplearning4j_tpu.nn import (
        InputType, MultiLayerNetwork, NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.util import FaultTolerantTrainer

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    xs = rng.standard_normal((32, 4)).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]

    class FlakyIterator:
        """Fails once mid-epoch after a checkpoint exists (simulated device
        failure), then works."""

        def __init__(self):
            self.failures = 0

        def reset(self):
            pass

        def __iter__(self):
            from deeplearning4j_tpu.data import DataSet

            for i in range(6):
                if self.failures == 0 and net.iteration >= 3:
                    self.failures += 1
                    raise RuntimeError("simulated device failure")
                yield DataSet(xs, ys)

    trainer = FaultTolerantTrainer(net, str(tmp_path / "ft"),
                                   checkpoint_every=2, max_restarts=2)
    trainer.fit(FlakyIterator(), epochs=2)
    assert trainer.ckpt.latest_step() is not None
    assert net.epoch >= 2
    assert np.isfinite(float(net.score_value))
