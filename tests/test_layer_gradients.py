"""Per-layer gradient checks — the DL4J gradientcheck suite parity
(CNNGradientCheckTest, GradientCheckTests; SURVEY.md §4: 'every layer type has
a gradcheck')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    LayerNormalization,
    OutputLayer,
    SubsamplingLayer,
)


def _cast_like(p, x):
    """Match input dtype to the (possibly fp64-upcast) param dtype — the
    gradcheck harness upcasts params only; ops follow the input dtype."""
    leaves = jax.tree_util.tree_leaves(p)
    return x.astype(leaves[0].dtype) if leaves else x


def _layer_loss_fn(layer, input_shape, rng, out_reduce=lambda y: jnp.sum(y**2)):
    key = jax.random.PRNGKey(0)
    params, state = layer.initialize(key, input_shape)
    x = jnp.asarray(rng.standard_normal((2,) + tuple(input_shape)))

    def loss(p):
        state64 = jax.tree_util.tree_map(lambda s: s.astype(jax.tree_util.tree_leaves(p)[0].dtype), state)
        y, _ = layer.apply(p, state64, _cast_like(p, x), training=True)
        return out_reduce(y)

    return loss, params


@pytest.mark.parametrize(
    "layer,shape",
    [
        (DenseLayer(n_in=5, n_out=4, activation="tanh"), (5,)),
        (ConvolutionLayer(n_out=3, kernel_size=(3, 3), padding="VALID", activation="sigmoid"), (6, 6, 2)),
        (BatchNormalization(), (4,)),
        (LayerNormalization(), (6,)),
    ],
)
def test_layer_param_gradients(layer, shape, rng):
    loss, params = _layer_loss_fn(layer, shape, rng)
    res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
    assert res.passed, f"{type(layer).__name__}: {res}"


def test_output_layer_loss_gradients(rng):
    layer = OutputLayer(n_in=6, n_out=4, loss="mcxent", activation="softmax")
    key = jax.random.PRNGKey(0)
    params, state = layer.initialize(key, (6,))
    x = jnp.asarray(rng.standard_normal((3, 6)))
    y = jnp.asarray(np.eye(4)[[0, 2, 3]])

    def loss(p):
        return layer.compute_loss(p, state, _cast_like(p, x), _cast_like(p, y), training=False)

    res = gradcheck.check_model_gradients(loss, params)
    assert res.passed, res


def test_embedding_layer_gradients(rng):
    layer = EmbeddingLayer(n_in=7, n_out=3)
    key = jax.random.PRNGKey(1)
    params, state = layer.initialize(key, ())
    ids = jnp.array([0, 3, 3, 6])

    def loss(p):
        y, _ = layer.apply(p, state, ids)
        return jnp.sum(y.astype(jax.tree_util.tree_leaves(p)[0].dtype)**2)

    res = gradcheck.check_model_gradients(loss, params)
    assert res.passed, res


def test_whole_network_gradients(rng):
    """End-to-end: conv -> pool -> dense -> output loss, all params checked."""
    layers = [
        ConvolutionLayer(n_out=2, kernel_size=(3, 3), padding="VALID", activation="tanh"),
        SubsamplingLayer(kernel_size=(2, 2)),
        DenseLayer(n_in=2 * 2 * 2, n_out=5, activation="relu"),
        OutputLayer(n_in=5, n_out=3, loss="mcxent", activation="softmax"),
    ]
    key = jax.random.PRNGKey(0)
    params, states, cur = [], [], (6, 6, 1)
    for lyr in layers:
        key, sub = jax.random.split(key)
        p, s = lyr.initialize(sub, cur)
        params.append(p)
        states.append(s)
        cur = lyr.output_shape(cur)
    x = jnp.asarray(rng.standard_normal((2, 6, 6, 1)))
    y = jnp.asarray(np.eye(3)[[0, 2]])

    def loss(ps):
        h = _cast_like(ps, x)
        for lyr, p, s in zip(layers[:-1], ps[:-1], states[:-1]):
            h, _ = lyr.apply(p, s, h, training=False)
        return layers[-1].compute_loss(ps[-1], states[-1], h, _cast_like(ps, y), training=False)

    res = gradcheck.check_model_gradients(loss, params, eps=1e-5, max_rel_error=1e-3)
    assert res.passed, res


def test_global_pooling_gradient_flow(rng):
    layer = GlobalPoolingLayer(pooling_type="avg")
    x = jnp.asarray(rng.standard_normal((2, 4, 4, 3)))

    def f(x):
        y, _ = layer.apply({}, {}, x)
        return jnp.sum(y**2)

    res = gradcheck.check_gradients(f, [x])
    assert res.passed, res
