"""Arbiter hyperparameter search + RL4J-parity DQN/A2C.

Reference test parity: arbiter's optimization runner tests and rl4j's
SimpleToy-based learning tests (SURVEY.md §2.2 J21)."""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    FixedValue,
    GridSearchCandidateGenerator,
    IntegerParameterSpace,
    MaxCandidatesCondition,
    OptimizationRunner,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.rl4j import (
    A2CConfiguration,
    A2CDiscreteDense,
    CartPole,
    QLearningConfiguration,
    QLearningDiscreteDense,
    SimpleToyMDP,
)


class TestArbiter:
    def test_spaces_sample_within_bounds(self):
        rng = np.random.default_rng(0)
        c = ContinuousParameterSpace(1e-4, 1e-1, log_scale=True)
        assert all(1e-4 <= c.sample(rng) <= 1e-1 for _ in range(50))
        i = IntegerParameterSpace(3, 7)
        assert set(i.grid(10)) == {3, 4, 5, 6, 7}
        d = DiscreteParameterSpace("a", "b")
        assert d.sample(rng) in ("a", "b")

    def test_random_search_finds_minimum(self):
        space = {"x": ContinuousParameterSpace(-2.0, 2.0),
                 "tag": FixedValue("v")}
        runner = OptimizationRunner(
            space, RandomSearchGenerator(64, seed=1),
            model_builder=lambda c: c,
            score_fn=lambda c: (c["x"] - 0.5) ** 2,
            minimize=True)
        res = runner.execute()
        assert abs(res.best_candidate["x"] - 0.5) < 0.2
        assert len(res.results) == 64
        assert res.best_candidate["tag"] == "v"

    def test_grid_search_enumerates_product(self):
        space = {"a": IntegerParameterSpace(0, 1),
                 "b": DiscreteParameterSpace("x", "y", "z")}
        runner = OptimizationRunner(
            space, GridSearchCandidateGenerator(),
            model_builder=lambda c: c, score_fn=lambda c: 0.0)
        res = runner.execute()
        assert len(res.results) == 6

    def test_failed_candidates_recorded_not_fatal(self):
        def build(c):
            if c["x"] > 0:
                raise RuntimeError("bad config")
            return c

        runner = OptimizationRunner(
            {"x": DiscreteParameterSpace(-1, 1)},
            GridSearchCandidateGenerator(),
            model_builder=build, score_fn=lambda c: c["x"])
        res = runner.execute()
        errs = [r for r in res.results if r.error]
        assert len(errs) == 1 and math.isnan(errs[0].score)
        assert res.best_candidate == {"x": -1}

    def test_termination_condition(self):
        runner = OptimizationRunner(
            {"x": ContinuousParameterSpace(0, 1)},
            RandomSearchGenerator(100, seed=0),
            model_builder=lambda c: c, score_fn=lambda c: c["x"],
            termination_conditions=[MaxCandidatesCondition(5)])
        assert len(runner.execute().results) == 5

    def test_network_hyperparam_search(self, rng):
        from deeplearning4j_tpu.nn import (
            InputType, MultiLayerNetwork, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        xs = rng.standard_normal((64, 4)).astype(np.float32)
        ys = np.eye(2, dtype=np.float32)[(xs.sum(1) > 0).astype(int)]

        def build(c):
            conf = (NeuralNetConfiguration.builder().seed(0)
                    .updater(Adam(c["lr"])).list()
                    .layer(DenseLayer(n_in=4, n_out=c["hidden"], activation="relu"))
                    .layer(OutputLayer(n_in=c["hidden"], n_out=2, loss="mcxent",
                                       activation="softmax"))
                    .set_input_type(InputType.feed_forward(4)).build())
            return MultiLayerNetwork(conf).init().fit(xs, ys, epochs=30)

        res = OptimizationRunner(
            {"lr": DiscreteParameterSpace(1e-4, 1e-2),
             "hidden": IntegerParameterSpace(8, 16)},
            RandomSearchGenerator(4, seed=0),
            model_builder=build,
            score_fn=lambda net: net.score(x=xs, y=ys)).execute()
        assert res.best_score < 0.6
        assert res.best_model is not None


class TestRL:
    def test_dqn_learns_toy_chain(self):
        mdp = SimpleToyMDP(length=6)
        conf = QLearningConfiguration(
            max_step=4000, epsilon_nb_step=1500, batch_size=32,
            hidden=(32,), target_dqn_update_freq=50, seed=1)
        learner = QLearningDiscreteDense(mdp, conf).train()
        policy = learner.get_policy()
        # optimal play walks the chain: reward 0.1*(L-1) + 1.0
        total = policy.play(SimpleToyMDP(length=6))
        assert total >= 1.0, total

    def test_double_dqn_learns_toy_chain(self):
        """rl4j doubleDQN parity: online-argmax / target-eval bootstrap
        (DoubleDQN target computer) must also solve the chain."""
        mdp = SimpleToyMDP(length=6)
        conf = QLearningConfiguration(
            max_step=4000, epsilon_nb_step=1500, batch_size=32,
            hidden=(32,), target_dqn_update_freq=50, seed=1,
            double_dqn=True)
        learner = QLearningDiscreteDense(mdp, conf).train()
        total = learner.get_policy().play(SimpleToyMDP(length=6))
        assert total >= 1.0, total

    def test_double_dqn_target_math(self):
        """The double-DQN target must use Q_target at the ONLINE argmax —
        distinguishable from max(Q_target) when the two nets disagree."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.rl4j import dqn as D

        mdp = SimpleToyMDP(length=4)
        conf = QLearningConfiguration(hidden=(8,), seed=0, double_dqn=True,
                                      gamma=1.0, reward_factor=1.0)
        learner = QLearningDiscreteDense(mdp, conf)
        # force disagreement: negate ONLY the output layer, so
        # q_target == -q_online exactly and argmax(target) == argmin(online)
        # on every row (negating every layer — the old construction — runs
        # the negated weights through relu, which happens to preserve the
        # argmax for this seed and made the sanity check below flaky)
        learner.target_params = learner.params[:-1] + [
            jax.tree_util.tree_map(lambda x: -x, learner.params[-1])]
        s2 = jnp.asarray(np.random.default_rng(0).normal(
            size=(3, mdp.obs_size)).astype(np.float32))
        q_online = D._mlp_apply(learner.params, s2)
        q_target = D._mlp_apply(learner.target_params, s2)
        a_star = jnp.argmax(q_online, axis=-1)
        expected = jnp.take_along_axis(q_target, a_star[:, None], 1)[:, 0]
        standard = jnp.max(q_target, axis=-1)
        # sanity: the two targets differ on this construction
        assert not np.allclose(expected, standard)
        # one train call must run without error under the flag
        s = jnp.zeros((3, mdp.obs_size))
        a = jnp.zeros((3,), jnp.int32)
        r = jnp.ones((3,))
        done = jnp.zeros((3,))
        learner._train(learner.params, learner.target_params,
                       learner.opt_state, jnp.asarray(0), s, a, r, s2, done)

    @pytest.mark.slow
    def test_dqn_cartpole_improves(self):
        conf = QLearningConfiguration(
            max_step=8000, epsilon_nb_step=4000, batch_size=64,
            hidden=(64, 64), target_dqn_update_freq=200, seed=0)
        learner = QLearningDiscreteDense(CartPole(seed=0), conf).train()
        policy = learner.get_policy()
        score = np.mean([policy.play(CartPole(seed=s)) for s in range(5)])
        assert score > 100, score  # random policy scores ~20

    def test_a2c_learns_toy_chain(self):
        conf = A2CConfiguration(max_updates=300, num_envs=4, n_steps=8,
                                hidden=(32,), seed=0)
        learner = A2CDiscreteDense(lambda: SimpleToyMDP(length=6), conf).train()
        total = learner.get_policy().play(SimpleToyMDP(length=6))
        assert total >= 1.0, total


class TestA3C:
    @pytest.mark.slow
    def test_a3c_async_learns_toy_chain(self):
        """ASYNC A3C (VERDICT r3 J21 tail): 4 actor-learner threads, stale
        gradients, shared Adam under a lock — learns the toy chain.

        slow-marked (r19 tier-1 budget, ~31s on the current host): the
        RL learn-on-toy-chain seam keeps its fast DQN/double-DQN
        siblings in tier-1; the async worker machinery itself still
        proves out in every full-CI pass."""
        from deeplearning4j_tpu.rl4j import A3CConfiguration, A3CDiscreteDense

        conf = A3CConfiguration(max_updates=400, num_threads=4, n_steps=8,
                                hidden=(32,), seed=0)
        learner = A3CDiscreteDense(lambda: SimpleToyMDP(length=6),
                                   conf).train()
        assert learner._updates_done >= conf.max_updates
        total = learner.get_policy().play(SimpleToyMDP(length=6))
        assert total >= 1.0, total


class TestGeneticSearch:
    def test_genetic_beats_its_first_generation(self):
        """GeneticSearchCandidateGenerator parity: population breeding must
        IMPROVE across generations on a smooth objective (and beat plain
        random search at equal budget)."""
        from deeplearning4j_tpu.arbiter import (
            GeneticSearchCandidateGenerator,
            OptimizationRunner,
        )

        space = {"x": ContinuousParameterSpace(-4.0, 4.0),
                 "y": ContinuousParameterSpace(-4.0, 4.0)}

        def objective(c):
            return (c["x"] - 1.0) ** 2 + (c["y"] + 2.0) ** 2

        gen = GeneticSearchCandidateGenerator(
            population_size=10, generations=8, seed=3)
        runner = OptimizationRunner(
            space, gen, model_builder=lambda c: c,
            score_fn=objective, minimize=True)
        res = runner.execute()
        pop = gen.population_size
        first_gen_best = min(r.score for r in res.results[:pop])
        assert res.best_score < first_gen_best, \
            (res.best_score, first_gen_best)
        assert res.best_score < 0.15, res.best_score

        rnd = RandomSearchGenerator(num_candidates=pop * 8, seed=3)
        rnd_runner = OptimizationRunner(
            space, rnd, model_builder=lambda c: c, score_fn=objective,
            minimize=True)
        rnd_best = rnd_runner.execute().best_score
        assert res.best_score <= rnd_best, (res.best_score, rnd_best)

    def test_genetic_survives_failing_candidates(self):
        from deeplearning4j_tpu.arbiter import (
            GeneticSearchCandidateGenerator,
            OptimizationRunner,
        )

        space = {"x": ContinuousParameterSpace(-1.0, 1.0)}
        calls = []

        def flaky(c):
            calls.append(c)
            if len(calls) % 3 == 0:
                raise RuntimeError("boom")
            return c["x"] ** 2

        gen = GeneticSearchCandidateGenerator(
            population_size=6, generations=3, seed=0)
        res = OptimizationRunner(space, gen, model_builder=lambda c: c,
                                 score_fn=flaky, minimize=True).execute()
        assert res.best_candidate is not None
        assert sum(1 for r in res.results if r.error) > 0
