"""ComputationGraph tests — ComputationGraphTest / graph-vertex gradcheck
parity (SURVEY.md §4: every vertex type exercised forward + gradient)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.gradcheck import check_model_gradients
from deeplearning4j_tpu.data import DataSet, MultiDataSet
from deeplearning4j_tpu.nn import (
    ComputationGraph,
    ComputationGraphConfiguration,
    InputType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.vertices import (
    ElementWiseVertex,
    L2NormalizeVertex,
    MergeVertex,
    ReshapeVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)


def _two_branch_graph(updater=None):
    """in → dense1 → {branch a, branch b} → merge → out (3-class)."""
    return (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater(updater or Adam(0.01))
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        .add_layer("a", DenseLayer(n_in=8, n_out=6, activation="relu"), "d1")
        .add_layer("b", DenseLayer(n_in=8, n_out=6, activation="relu"), "d1")
        .add_vertex("merge", MergeVertex(), "a", "b")
        .add_layer("out", OutputLayer(n_in=12, n_out=3), "merge")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4))
        .build()
    )


def _toy_data(rng, n=64, n_in=4, n_out=3):
    x = rng.normal(size=(n, n_in)).astype(np.float32)
    cls = (np.abs(x).sum(axis=1) * 2).astype(int) % n_out
    y = np.eye(n_out, dtype=np.float32)[cls]
    return x, y


def test_build_topo_and_shapes():
    net = ComputationGraph(_two_branch_graph()).init()
    assert net._shape_of["merge"] == (12,)
    assert net._shape_of["out"] == (3,)
    assert net.num_params() == (4 * 8 + 8) + 2 * (8 * 6 + 6) + (12 * 3 + 3)


def test_forward_output_shape(rng):
    net = ComputationGraph(_two_branch_graph()).init()
    x, _ = _toy_data(rng)
    out = net.output(x)
    assert out.shape == (64, 3)
    np.testing.assert_allclose(np.sum(np.asarray(out), axis=1), 1.0, atol=1e-5)


def test_fit_learns(rng):
    net = ComputationGraph(_two_branch_graph()).init()
    x, y = _toy_data(rng, n=256)
    s0 = net.score(x=x, y=y)
    for _ in range(150):
        net.fit(x, y)
    assert net.score(x=x, y=y) < s0 * 0.85


def test_residual_elementwise_add(rng):
    """Residual connection: out = dense2(relu(dense1(x)) + x)."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater(Sgd(0.1))
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=5, n_out=5, activation="relu"), "in")
        .add_vertex("res", ElementWiseVertex(op="add"), "d1", "in")
        .add_layer("out", OutputLayer(n_in=5, n_out=2), "res")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(5))
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _toy_data(rng, n=32, n_in=5, n_out=2)
    # forward value check: res == relu(d1(x)) + x
    acts = net.feed_forward(x)
    manual = np.maximum(
        np.asarray(x) @ np.asarray(net.params["d1"]["W"]) + np.asarray(net.params["d1"]["b"]),
        0,
    ) + np.asarray(x)
    np.testing.assert_allclose(np.asarray(acts["res"]), manual, rtol=1e-5)
    s0 = net.score(x=x, y=y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score(x=x, y=y) < s0


def test_multi_input_multi_output(rng):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .updater(Adam(0.01))
        .graph_builder()
        .add_inputs("ina", "inb")
        .add_layer("da", DenseLayer(n_in=3, n_out=4, activation="tanh"), "ina")
        .add_layer("db", DenseLayer(n_in=2, n_out=4, activation="tanh"), "inb")
        .add_vertex("m", MergeVertex(), "da", "db")
        .add_layer("out1", OutputLayer(n_in=8, n_out=2), "m")
        .add_layer("out2", OutputLayer(n_in=8, n_out=3), "m")
        .set_outputs("out1", "out2")
        .set_input_types(InputType.feed_forward(3), InputType.feed_forward(2))
        .build()
    )
    net = ComputationGraph(conf).init()
    xa = rng.normal(size=(16, 3)).astype(np.float32)
    xb = rng.normal(size=(16, 2)).astype(np.float32)
    y1 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
    y2 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    o1, o2 = net.output(xa, xb)
    assert o1.shape == (16, 2) and o2.shape == (16, 3)
    mds = MultiDataSet(features=[xa, xb], labels=[y1, y2])
    s0 = net.score(x=[xa, xb], y=[y1, y2])
    for _ in range(80):
        net.fit([mds])
    assert net.score(x=[xa, xb], y=[y1, y2]) < s0


def test_implicit_merge_on_multi_input_layer(rng):
    """A layer with 2 declared inputs gets an implicit MergeVertex (reference
    ComputationGraphConfiguration behavior)."""
    conf = (
        NeuralNetConfiguration.builder()
        .graph_builder()
        .add_inputs("a", "b")
        .add_layer("out", OutputLayer(n_in=5, n_out=2), "a", "b")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(3), InputType.feed_forward(2))
        .build()
    )
    net = ComputationGraph(conf).init()
    o = net.output(
        rng.normal(size=(4, 3)).astype(np.float32),
        rng.normal(size=(4, 2)).astype(np.float32),
    )
    assert o.shape == (4, 2)


def test_cnn_graph_with_pooling(rng):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .updater(Adam(0.005))
        .graph_builder()
        .add_inputs("img")
        .add_layer("c1", ConvolutionLayer(n_out=4, kernel_size=(3, 3), activation="relu"), "img")
        .add_layer("p1", SubsamplingLayer(kernel_size=(2, 2)), "c1")
        .add_layer("c2", ConvolutionLayer(n_out=8, kernel_size=(3, 3), activation="relu"), "p1")
        .add_vertex("gap", ScaleVertex(scale=1.0), "c2")
        .add_layer("pool", GlobalPoolingLayer(), "gap")
        .add_layer("out", OutputLayer(n_in=8, n_out=2), "pool")
        .set_outputs("out")
        .set_input_types(InputType.convolutional(8, 8, 1))
        .build()
    )
    net = ComputationGraph(conf).init()
    x = rng.normal(size=(6, 8, 8, 1)).astype(np.float32)
    assert net.output(x).shape == (6, 2)


@pytest.mark.parametrize(
    "vertex,n_inputs,in_shape,expected_shape",
    [
        (MergeVertex(), 2, (4,), (8,)),
        (ElementWiseVertex(op="add"), 2, (4,), (4,)),
        (ElementWiseVertex(op="subtract"), 2, (4,), (4,)),
        (ElementWiseVertex(op="product"), 2, (4,), (4,)),
        (ElementWiseVertex(op="average"), 3, (4,), (4,)),
        (ElementWiseVertex(op="max"), 2, (4,), (4,)),
        (SubsetVertex(from_idx=1, to_idx=2), 1, (4,), (2,)),
        (ScaleVertex(scale=2.5), 1, (4,), (4,)),
        (ShiftVertex(shift=1.0), 1, (4,), (4,)),
        (L2NormalizeVertex(), 1, (4,), (4,)),
        (ReshapeVertex(new_shape=(2, 2)), 1, (4,), (2, 2)),
    ],
)
def test_vertex_forward_and_shape(rng, vertex, n_inputs, in_shape, expected_shape):
    xs = [rng.normal(size=(3,) + in_shape).astype(np.float32) for _ in range(n_inputs)]
    out = vertex.apply(*[jnp.asarray(x) for x in xs])
    assert tuple(out.shape[1:]) == expected_shape
    assert vertex.output_shape(*[in_shape] * n_inputs) == expected_shape
    # differentiable through the vertex
    g = jax.grad(lambda *a: jnp.sum(vertex.apply(*a) ** 2))(*[jnp.asarray(x) for x in xs])
    assert np.all(np.isfinite(np.asarray(g)))


def test_stack_unstack(rng):
    a = rng.normal(size=(3, 4)).astype(np.float32)
    b = rng.normal(size=(3, 4)).astype(np.float32)
    stacked = StackVertex().apply(jnp.asarray(a), jnp.asarray(b))
    assert stacked.shape == (6, 4)
    back = UnstackVertex(index=1, num_stacked=2).apply(stacked)
    np.testing.assert_allclose(np.asarray(back), b)


def test_parallel_inference_serves_graph(rng):
    from deeplearning4j_tpu.parallel import ParallelInference

    net = ComputationGraph(_two_branch_graph()).init()
    pi = ParallelInference(net)
    x = rng.normal(size=(13, 4)).astype(np.float32)  # ragged vs 8 devices
    out = pi.output(x)
    assert out.shape == (13, 3)
    np.testing.assert_allclose(out, np.asarray(net.output(x)), rtol=2e-3, atol=1e-5)


def test_fit_multi_input_arrays(rng):
    conf = (
        NeuralNetConfiguration.builder()
        .graph_builder()
        .add_inputs("a", "b")
        .add_layer("out", OutputLayer(n_in=5, n_out=2), "a", "b")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(3), InputType.feed_forward(2))
        .build()
    )
    net = ComputationGraph(conf).init()
    xa = rng.normal(size=(4, 3)).astype(np.float32)
    xb = rng.normal(size=(4, 2)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
    net.fit([xa, xb], [y], epochs=2)
    assert np.isfinite(net.get_score())


def test_json_round_trip():
    conf = _two_branch_graph()
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.to_json() == s
    net = ComputationGraph(conf2).init()
    assert net._shape_of["out"] == (3,)


def test_graph_gradients_match_fd(rng):
    """fp64 central-difference gradcheck through merge + elementwise vertices
    (GradientCheckTestsComputationGraph parity)."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(13)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=3, n_out=4, activation="tanh"), "in")
        .add_layer("d2", DenseLayer(n_in=3, n_out=4, activation="sigmoid"), "in")
        .add_vertex("ew", ElementWiseVertex(op="product"), "d1", "d2")
        .add_vertex("mg", MergeVertex(), "ew", "d1")
        .add_layer("out", OutputLayer(n_in=8, n_out=2), "mg")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(3))
        .build()
    )
    net = ComputationGraph(conf).init()
    x = rng.normal(size=(5, 3))
    y = np.eye(2)[rng.integers(0, 2, 5)]

    def loss_fn(params):
        keys = {n.name: jax.random.PRNGKey(0) for n in net.topo if n.is_layer}
        loss, _ = net._loss(
            params, net.states, {"in": jnp.asarray(x)}, {"out": jnp.asarray(y)}, keys
        )
        return loss

    res = check_model_gradients(loss_fn, net.params)
    assert res.passed, repr(res)


def test_graph_mask_threading_and_fit_dataset(rng):
    """Sequence graph with attention: (B,T) masks reach mask-aware layers and
    the per-step loss; fit(DataSet) works (ComputationGraph mask parity)."""
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.nn.attention import SelfAttentionLayer
    from deeplearning4j_tpu.nn.recurrent import RnnOutputLayer

    gb = (NeuralNetConfiguration.builder().seed(0).updater(Adam(0.01))
          .graph_builder().add_inputs("in"))
    gb.add_layer("attn", SelfAttentionLayer(n_in=4, n_out=6, n_heads=2), "in")
    gb.add_layer("out", RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                       activation="softmax"), "attn")
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(4, 5))
    net = ComputationGraph(gb.build()).init()

    x = rng.standard_normal((3, 5, 4)).astype(np.float32)
    mask = np.ones((3, 5), np.float32)
    mask[0, 3:] = 0
    # masked keys don't leak into valid positions
    y1 = np.asarray(net.output(x, mask=mask))
    x2 = x.copy()
    x2[0, 3:] += 50.0
    y2 = np.asarray(net.output(x2, mask=mask))
    np.testing.assert_allclose(y1[0, :3], y2[0, :3], atol=1e-4)

    ids = rng.integers(0, 3, size=(3, 5))
    labels = np.eye(3, dtype=np.float32)[ids]
    ds = DataSet(x, labels, features_mask=mask, labels_mask=mask.copy())
    s0 = net.score(ds)
    net.fit(ds, epochs=12)
    assert net.score(ds) < s0
