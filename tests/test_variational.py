"""AutoEncoder + VariationalAutoencoder layers and MLN.pretrain().

Reference test parity: DL4J's variational gradcheck suite
(deeplearning4j-core gradientcheck/VaeGradientCheckTests.java) and the
unsupervised-pretraining integration tests (SURVEY.md §4) — path-cite, mount
empty this round.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.data import ArrayDataSetIterator, MnistDataSetIterator
from deeplearning4j_tpu.nn import (
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.variational import AutoEncoder, VariationalAutoencoder


class TestAutoEncoder:
    def test_pretrain_loss_gradcheck(self, rng):
        lyr = AutoEncoder(n_in=6, n_out=4, corruption_level=0.0)
        params, _ = lyr.initialize(jax.random.PRNGKey(0), (6,))
        x = jnp.asarray(rng.normal(size=(5, 6)))

        def loss(p):
            return lyr.pretrain_loss(p, x.astype(
                jax.tree_util.tree_leaves(p)[0].dtype), None)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    def test_denoising_reconstruction_improves(self, rng):
        # low-rank data: 8-dim features on a 3-dim manifold
        basis = rng.normal(size=(3, 8)).astype(np.float32)
        xs = (rng.normal(size=(256, 3)).astype(np.float32) @ basis)
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(0.01))
                .list()
                .layer(AutoEncoder(n_in=8, n_out=3, activation="identity",
                                   corruption_level=0.1))
                .layer(OutputLayer(n_in=3, n_out=2))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        lyr = net.layers[0]

        def recon_err(params):
            h = lyr.encode(params, jnp.asarray(xs))
            return float(jnp.mean(jnp.square(lyr.decode(params, h) - xs)))

        e0 = recon_err(net.params[0])
        it = ArrayDataSetIterator(xs, np.zeros((256, 2), np.float32), batch=64)
        net.pretrain_layer(0, it, epochs=30)
        e1 = recon_err(net.params[0])
        assert e1 < e0 * 0.5, (e0, e1)

    def test_supervised_apply_is_encoder(self, rng):
        lyr = AutoEncoder(n_in=6, n_out=4)
        params, state = lyr.initialize(jax.random.PRNGKey(0), (6,))
        x = jnp.asarray(rng.normal(size=(3, 6)), jnp.float32)
        y, _ = lyr.apply(params, state, x)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(lyr.encode(params, x)),
                                   atol=1e-7)


class TestVAE:
    @pytest.mark.parametrize("dist", ["gaussian", "bernoulli"])
    def test_pretrain_loss_gradcheck(self, rng, dist):
        lyr = VariationalAutoencoder(
            n_in=5, n_out=3, encoder_layer_sizes=(8,),
            decoder_layer_sizes=(8,), activation="tanh",
            reconstruction_distribution=dist)
        params, _ = lyr.initialize(jax.random.PRNGKey(0), (5,))
        raw = rng.normal(size=(4, 5))
        x = jnp.asarray(raw if dist == "gaussian"
                        else (raw > 0).astype(np.float64))
        key = jax.random.PRNGKey(7)

        def loss(p):
            return lyr.pretrain_loss(
                p, x.astype(jax.tree_util.tree_leaves(p)[0].dtype), key)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    def test_elbo_drops_on_mnist(self):
        it = MnistDataSetIterator(batch=128, train=True, n_examples=1024)
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
                .list()
                .layer(VariationalAutoencoder(
                    n_in=784, n_out=16, encoder_layer_sizes=(128,),
                    decoder_layer_sizes=(128,), activation="relu",
                    reconstruction_distribution="bernoulli"))
                .layer(OutputLayer(n_in=16, n_out=10))
                .set_input_type(InputType.feed_forward(784)).build())
        net = MultiLayerNetwork(conf).init()
        lyr = net.layers[0]
        ds = next(iter(it))
        x0 = jnp.asarray(ds.features.reshape(len(ds.features), -1))
        e0 = float(lyr.pretrain_loss(net.params[0], x0,
                                     jax.random.PRNGKey(0)))
        net.pretrain(it, epochs=8)
        e1 = float(lyr.pretrain_loss(net.params[0], x0,
                                     jax.random.PRNGKey(0)))
        assert e1 < e0 * 0.7, (e0, e1)
        # reconstruction of the latent mean resembles the input
        rec = np.asarray(lyr.reconstruct(net.params[0], x0))
        base = np.mean((np.asarray(x0) - np.asarray(x0).mean()) ** 2)
        err = np.mean((rec - np.asarray(x0)) ** 2)
        assert err < base, (err, base)

    def test_pretrain_then_fit(self, rng):
        """pretrain() then fit(): the reference's canonical unsupervised →
        supervised flow."""
        centers = rng.standard_normal((3, 8)) * 2.5
        ys = rng.integers(0, 3, 256)
        xs = (centers[ys] + rng.standard_normal((256, 8))).astype(np.float32)
        yoh = np.eye(3, dtype=np.float32)[ys]
        conf = (NeuralNetConfiguration.builder().seed(11).updater(Adam(0.01))
                .list()
                .layer(VariationalAutoencoder(
                    n_in=8, n_out=4, encoder_layer_sizes=(16,),
                    decoder_layer_sizes=(16,), activation="tanh"))
                .layer(OutputLayer(n_in=4, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        it = ArrayDataSetIterator(xs, yoh, batch=64)
        net.pretrain(it, epochs=10)
        net.fit(it, epochs=15)
        acc = (np.argmax(net.output(xs), 1) == ys).mean()
        assert acc > 0.8, acc

    def test_mixed_stack_pretrains_only_pretrain_layers(self, rng):
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01))
                .list()
                .layer(AutoEncoder(n_in=6, n_out=4, corruption_level=0.0))
                .layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
                .layer(OutputLayer(n_in=4, n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        xs = rng.normal(size=(64, 6)).astype(np.float32)
        dense_before = np.asarray(net.params[1]["W"]).copy()
        ae_before = np.asarray(net.params[0]["W"]).copy()
        net.pretrain(ArrayDataSetIterator(
            xs, np.zeros((64, 2), np.float32), batch=32), epochs=3)
        assert not np.allclose(np.asarray(net.params[0]["W"]), ae_before)
        np.testing.assert_array_equal(np.asarray(net.params[1]["W"]),
                                      dense_before)


class TestGraphPretrain:
    """ComputationGraph.pretrain parity (the reference pretrains CG layer
    vertices too)."""

    def test_cg_vae_pretrain_then_fit(self, rng):
        from deeplearning4j_tpu.nn import ComputationGraph

        centers = rng.standard_normal((3, 8)) * 2.5
        ys = rng.integers(0, 3, 192)
        xs = (centers[ys] + rng.standard_normal((192, 8))).astype(np.float32)
        yoh = np.eye(3, dtype=np.float32)[ys]
        conf = (NeuralNetConfiguration.builder().seed(4).updater(Adam(0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("vae", VariationalAutoencoder(
                    n_in=8, n_out=4, encoder_layer_sizes=(16,),
                    decoder_layer_sizes=(16,), activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=3, loss="mcxent",
                                              activation="softmax"), "vae")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(8))
                .build())
        net = ComputationGraph(conf).init()
        vae = next(n.node for n in net.topo if n.name == "vae")
        e0 = float(vae.pretrain_loss(net.params["vae"], jnp.asarray(xs),
                                     jax.random.PRNGKey(0)))
        it = ArrayDataSetIterator(xs, yoh, batch=64)
        net.pretrain(it, epochs=10)
        e1 = float(vae.pretrain_loss(net.params["vae"], jnp.asarray(xs),
                                     jax.random.PRNGKey(0)))
        assert e1 < e0, (e0, e1)
        net.fit(xs, yoh, epochs=30)
        acc = (np.argmax(np.asarray(net.output(xs)), 1) == ys).mean()
        assert acc > 0.8, acc
