"""Full-architecture import regression corpus (VERDICT r3 missing #1).

Reference parity: the reference regression-tests its TF importer against
hundreds of COMPLETE frozen graphs with recorded goldens
(nd4j-tf-graph-tests, TFGraphTestAllSameDiff-style runner — SURVEY.md §4),
not just per-op blocks. Offline equivalent here:

- TF side: every frozen ``tf.keras.applications`` architecture below is
  built in-test (random init — a random-init graph exercises the import
  rules exactly as well as pretrained bits), frozen with
  ``convert_variables_to_constants_v2``, imported, and matched against
  TF's own forward output at tight fp32 tolerance.
- ONNX side: real published torch architectures — ResNet-18 (He et al.),
  a MobileNetV3-flavoured SE/hardswish block net, torch LSTM/GRU seq
  models, and transformers' BERT / GPT-2 / DistilBERT (random-init
  configs; no torchvision/onnx in the image, so conv nets are standard
  architectures written with torch.nn and everything exports through
  ``torch.onnx.export``).
- Fine-tune: two of the conv nets train one/two steps after import
  (convert_to_variable → fit), proving the imported graphs are not just
  forward-correct but trainable.

Small input resolutions keep single-core CPU runtime sane; goldens run on
CPU (conftest pins the platform) where fp32 matches the source framework.
"""

import io
from typing import List

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
torch = pytest.importorskip("torch")

from deeplearning4j_tpu.imports import import_graph_def, import_onnx  # noqa: E402


# --------------------------------------------------------------------- TF


def _freeze_keras(model):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    shp = model.input_shape[1:]
    conc = tf.function(lambda v: model(v, training=False)).get_concrete_function(
        tf.TensorSpec((None,) + shp, tf.float32))
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_name = frozen.inputs[0].name.split(":")[0]
    out_name = frozen.outputs[0].name
    return gd, frozen, in_name, out_name


RES = 64
_TF_APPS = {
    # name -> builder; include_top=False + pooling exercises every conv/BN/
    # activation block (the head is a plain Dense, covered elsewhere)
    "ResNet50": lambda: tf.keras.applications.ResNet50(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
    "ResNet50V2": lambda: tf.keras.applications.ResNet50V2(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
    "MobileNetV2": lambda: tf.keras.applications.MobileNetV2(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
    "MobileNetV3Small": lambda: tf.keras.applications.MobileNetV3Small(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg",
        include_preprocessing=True),
    "EfficientNetB0": lambda: tf.keras.applications.EfficientNetB0(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
    "DenseNet121": lambda: tf.keras.applications.DenseNet121(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
    "InceptionV3": lambda: tf.keras.applications.InceptionV3(
        weights=None, include_top=False, input_shape=(96, 96, 3), pooling="avg"),
    "VGG16": lambda: tf.keras.applications.VGG16(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
    "Xception": lambda: tf.keras.applications.Xception(
        weights=None, include_top=False, input_shape=(96, 96, 3), pooling="avg"),
    "NASNetMobile": lambda: tf.keras.applications.NASNetMobile(
        weights=None, include_top=False, input_shape=(RES, RES, 3), pooling="avg"),
}


class TestTFFullModelCorpus:
    # tier-1 runtime guard (ISSUE 11 satellite): the two heaviest goldens
    # (NASNetMobile ~15s, InceptionV3 ~11s) carry the slow mark — eight
    # cheaper corpus goldens keep the import seam covered in tier-1, and
    # the full-suite CI leg still runs every model
    @pytest.mark.parametrize(
        "name",
        [pytest.param(n, marks=pytest.mark.slow)
         if n in ("NASNetMobile", "InceptionV3") else n
         for n in sorted(_TF_APPS)])
    def test_forward_golden(self, name, rng):
        tf.keras.utils.set_random_seed(7)
        model = _TF_APPS[name]()
        gd, frozen, in_name, out_name = _freeze_keras(model)
        shp = model.input_shape[1:]
        x = rng.normal(size=(2,) + shp).astype(np.float32)
        golden = frozen(tf.constant(x))
        if isinstance(golden, (list, tuple)):
            golden = golden[0]
        golden = np.asarray(golden)

        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_name]
        res = np.asarray(sd.output({in_name: x}, [key])[key])
        # fp32 CPU both sides; rel tol covers conv reduction-order noise
        np.testing.assert_allclose(res, golden, atol=1e-4, rtol=1e-4)

    # NOTE: MobileNetV2/EfficientNet at random init collapse activations to
    # ~1e-12 through their deep inference-mode BN stacks — gradients vanish
    # below fp32 resolution, which is an init property, not an import
    # property. ResNet50 (residual skips preserve scale) and VGG16 (no BN)
    # are the trainable-at-random-init picks.
    @pytest.mark.parametrize("name", ["ResNet50", "VGG16"])
    def test_finetune_one_step(self, name, rng):
        """Imported frozen graph → convert conv kernels to variables →
        fit: the loss must move and stay finite (trainability proof)."""
        from deeplearning4j_tpu.nn.updaters import Adam
        from deeplearning4j_tpu.samediff import TrainingConfig

        tf.keras.utils.set_random_seed(7)
        builder = {
            "ResNet50": lambda: tf.keras.applications.ResNet50(
                weights=None, include_top=False, input_shape=(32, 32, 3),
                pooling="avg"),
            "VGG16": lambda: tf.keras.applications.VGG16(
                weights=None, include_top=False, input_shape=(32, 32, 3),
                pooling="avg"),
        }[name]
        model = builder()
        gd, frozen, in_name, out_name = _freeze_keras(model)
        sd = import_graph_def(gd)

        kernels = [n for n, v in sd._arrays.items() if np.asarray(v).ndim == 4]
        assert kernels, "no conv kernels found in imported graph"
        sd.convert_to_variable(*kernels)

        C = 2
        feat = sd.get_variable(sd.tf_name_map[out_name])
        width = int(feat.shape[-1])
        w = sd.constant(
            (rng.normal(size=(width, C)) * 0.05).astype(np.float32), "head_w")
        sd.convert_to_variable("head_w")
        logits = sd._op("matmul", [feat, w])
        y = sd.placeholder("y", shape=(-1, C))
        loss = sd.loss.softmaxCrossEntropy(logits, y)
        sd.set_loss_variables(loss)
        sd.set_training_config(TrainingConfig(
            updater=Adam(1e-2),
            data_set_feature_mapping=[in_name],
            data_set_label_mapping=["y"]))

        x = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
        labels = np.eye(C, dtype=np.float32)[rng.integers(0, C, size=4)]
        k0 = kernels[0]
        before = np.asarray(sd._arrays[k0]).copy()
        hist = sd.fit((x, labels), epochs=2)
        assert np.isfinite(hist).all(), hist
        assert hist[1] != hist[0], "loss did not move"
        assert not np.array_equal(np.asarray(sd._arrays[k0]), before), \
            "converted kernel did not update"


# ------------------------------------------------------------------- ONNX


def _export_onnx(model, x):
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

    # the TorchScript exporter builds+serializes the ModelProto itself and
    # only needs the `onnx` package (absent in this image) to splice in
    # onnxscript custom functions, which none of these models use
    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda mb, co: mb
    try:
        buf = io.BytesIO()
        torch.onnx.export(model, (x,), buf, input_names=["x"],
                          output_names=["y"], dynamo=False)
        return buf.getvalue()
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


class _BasicBlock(torch.nn.Module):
    """ResNet BasicBlock (He et al. 2015)."""

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        nn = torch.nn
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))
        self.relu = nn.ReLU()

    def forward(self, x):
        idn = x if self.down is None else self.down(x)
        h = self.relu(self.bn1(self.conv1(x)))
        h = self.bn2(self.conv2(h))
        return self.relu(h + idn)


class _ResNet18(torch.nn.Module):
    def __init__(self, classes=10):
        super().__init__()
        nn = torch.nn
        self.stem = nn.Sequential(
            nn.Conv2d(3, 64, 7, 2, 3, bias=False), nn.BatchNorm2d(64),
            nn.ReLU(), nn.MaxPool2d(3, 2, 1))
        blocks, cin = [], 64
        for cout, stride in [(64, 1), (64, 1), (128, 2), (128, 1),
                             (256, 2), (256, 1), (512, 2), (512, 1)]:
            blocks.append(_BasicBlock(cin, cout, stride))
            cin = cout
        self.blocks = nn.Sequential(*blocks)
        self.pool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512, classes)

    def forward(self, x):
        return self.fc(self.pool(self.blocks(self.stem(x))).flatten(1))


class _MobileSE(torch.nn.Module):
    """MobileNetV3-flavoured: depthwise separable + SE + hardswish."""

    def __init__(self):
        super().__init__()
        nn = torch.nn
        self.stem = nn.Sequential(nn.Conv2d(3, 16, 3, 2, 1, bias=False),
                                  nn.BatchNorm2d(16), nn.Hardswish())
        self.dw = nn.Sequential(
            nn.Conv2d(16, 16, 3, 1, 1, groups=16, bias=False),
            nn.BatchNorm2d(16), nn.ReLU())
        self.se_pool = nn.AdaptiveAvgPool2d(1)
        self.se_fc1 = nn.Conv2d(16, 8, 1)
        self.se_fc2 = nn.Conv2d(8, 16, 1)
        self.pw = nn.Sequential(nn.Conv2d(16, 32, 1, bias=False),
                                nn.BatchNorm2d(32), nn.Hardswish())
        self.head = nn.Linear(32, 7)

    def forward(self, x):
        h = self.dw(self.stem(x))
        s = torch.sigmoid(
            self.se_fc2(torch.relu(self.se_fc1(self.se_pool(h)))))
        h = self.pw(h * s)
        return self.head(h.mean(dim=(2, 3)))


class _LSTMSeq(torch.nn.Module):
    def __init__(self):
        super().__init__()
        nn = torch.nn
        self.emb = nn.Embedding(50, 16)
        self.lstm = nn.LSTM(16, 32, num_layers=2, batch_first=True)
        self.head = nn.Linear(32, 5)

    def forward(self, tok):
        h, _ = self.lstm(self.emb(tok))
        return self.head(h[:, -1])


class _GRUSeq(torch.nn.Module):
    def __init__(self):
        super().__init__()
        nn = torch.nn
        self.emb = nn.Embedding(50, 16)
        self.gru = nn.GRU(16, 32, batch_first=True, bidirectional=True)
        self.head = nn.Linear(64, 5)

    def forward(self, tok):
        h, _ = self.gru(self.emb(tok))
        return self.head(h[:, -1])


def _hf_wrap(model):
    class Wrap(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.m = model

        def forward(self, tok):
            return self.m(input_ids=tok).last_hidden_state

    return Wrap()


def _bert_tiny():
    from transformers import BertConfig, BertModel

    return _hf_wrap(BertModel(BertConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64)))


def _gpt2_tiny():
    from transformers import GPT2Config, GPT2Model

    return _hf_wrap(GPT2Model(GPT2Config(
        vocab_size=100, n_positions=64, n_embd=32, n_layer=2, n_head=2)))


def _distilbert_tiny():
    from transformers import DistilBertConfig, DistilBertModel

    return _hf_wrap(DistilBertModel(DistilBertConfig(
        vocab_size=100, dim=32, n_layers=2, n_heads=2, hidden_dim=64,
        max_position_embeddings=64)))


_ONNX_MODELS = {
    "resnet18": (_ResNet18, lambda: torch.randn(2, 3, 64, 64)),
    "mobile_se": (_MobileSE, lambda: torch.randn(2, 3, 32, 32)),
    "lstm_seq": (_LSTMSeq, lambda: torch.randint(0, 50, (2, 12))),
    "gru_seq": (_GRUSeq, lambda: torch.randint(0, 50, (2, 12))),
    "bert_tiny": (_bert_tiny, lambda: torch.randint(0, 100, (2, 10))),
    "gpt2_tiny": (_gpt2_tiny, lambda: torch.randint(0, 100, (2, 10))),
    "distilbert_tiny": (_distilbert_tiny, lambda: torch.randint(0, 100, (2, 10))),
}


class TestONNXFullModelCorpus:
    @pytest.mark.parametrize("name", sorted(_ONNX_MODELS))
    def test_forward_golden(self, name):
        torch.manual_seed(0)
        mk, mkx = _ONNX_MODELS[name]
        model = mk().eval()
        x = mkx()
        data = _export_onnx(model, x)
        sd = import_onnx(data)
        out = np.asarray(sd.output({"x": x.numpy()}, ["y"])["y"])
        with torch.no_grad():
            golden = model(x).numpy()
        np.testing.assert_allclose(out, golden, atol=1e-4, rtol=1e-4)

    def test_resnet18_save_load_roundtrip(self, tmp_path):
        """Imported full-model graphs must survive serialization."""
        torch.manual_seed(0)
        model = _ResNet18().eval()
        x = torch.randn(1, 3, 64, 64)
        sd = import_onnx(_export_onnx(model, x))
        ref = np.asarray(sd.output({"x": x.numpy()}, ["y"])["y"])
        p = str(tmp_path / "rn18.sdz")
        sd.save(p)
        from deeplearning4j_tpu.samediff import SameDiff

        sd2 = SameDiff.load(p)
        out = np.asarray(sd2.output({"x": x.numpy()}, ["y"])["y"])
        np.testing.assert_allclose(out, ref, atol=1e-6)


# ----------------------------------------------------- ONNX control flow
# VERDICT r3 missing #3: Loop/If/Scan + Einsum. torch scripted control flow
# exports ONNX Loop/If subgraphs; the importer lowers them to ONE
# lax.while_loop / lax.scan / lax.cond custom node each (same collapse as
# the TF side's While/If — reference: samediff-import-onnx, path-cite).


class _ForLoopNet(torch.nn.Module):
    def forward(self, x):
        h = x
        for i in range(5):
            h = h * 0.5 + 1.0
        return h


class _WhileLoopNet(torch.nn.Module):
    def forward(self, x):
        h = x
        while h.sum() < 100.0:
            h = h * 2.0
        return h


class _CondNet(torch.nn.Module):
    def forward(self, x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 3.0
        return y


class _EinsumNet(torch.nn.Module):
    def forward(self, a, b):
        return torch.einsum("bij,bjk->bik", a, b)


class _GreedyDecode(torch.nn.Module):
    """toy greedy decoder: embed last token, fused cell, argmax — the
    'torch-exported greedy-decode loop imports and matches' criterion."""

    def __init__(self):
        super().__init__()
        nn = torch.nn
        self.emb = nn.Embedding(20, 16)
        self.cell = nn.Linear(32, 16)
        self.out = nn.Linear(16, 20)

    def forward(self, tok0: torch.Tensor, h0: torch.Tensor) -> torch.Tensor:
        tok = tok0
        h = h0
        outs: List[torch.Tensor] = []
        for i in range(6):
            e = self.emb(tok).squeeze(1)
            h = torch.tanh(self.cell(torch.cat([e, h], dim=1)))
            logits = self.out(h)
            tok = logits.argmax(dim=1, keepdim=True)
            outs.append(tok)
        return torch.cat(outs, dim=1)


class _OpTailNet(torch.nn.Module):
    """exercises the round-4 ONNX rule tail in one traced graph:
    Asin/Atan/Acos, ReduceLogSumExp, Celu, Shrink (torch Softshrink),
    HardSwish (torch's legacy exporter has no aten::sinh family symbolic;
    those _OUN entries map 1:1 onto registry ops with their own coverage)."""

    def forward(self, x):
        xc = torch.clamp(x, -0.9, 0.9)
        a = torch.asin(xc) + torch.atan(x) + torch.acos(xc)
        b = x * 0.1
        c = torch.logsumexp(x, dim=1, keepdim=True)
        d = torch.nn.functional.celu(x, alpha=0.7)
        e = torch.nn.functional.softshrink(x, lambd=0.3)
        f = torch.nn.functional.hardswish(x)
        return a + b + c + d + e + f


def _export_scripted(model, xs):
    from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda mb, co: mb
    try:
        buf = io.BytesIO()
        torch.onnx.export(torch.jit.script(model), tuple(xs), buf,
                          input_names=[f"x{i}" for i in range(len(xs))],
                          output_names=["y"], dynamo=False)
        return buf.getvalue()
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


class TestONNXControlFlow:
    def _match(self, model, xs, scripted=True, exact=True):
        data = _export_scripted(model, xs) if scripted else None
        if data is None:
            from torch.onnx._internal.torchscript_exporter import (
                onnx_proto_utils,
            )

            orig = onnx_proto_utils._add_onnxscript_fn
            onnx_proto_utils._add_onnxscript_fn = lambda mb, co: mb
            try:
                buf = io.BytesIO()
                torch.onnx.export(model, tuple(xs), buf,
                                  input_names=[f"x{i}" for i in range(len(xs))],
                                  output_names=["y"], dynamo=False)
                data = buf.getvalue()
            finally:
                onnx_proto_utils._add_onnxscript_fn = orig
        sd = import_onnx(data)
        feeds = {f"x{i}": v.numpy() for i, v in enumerate(xs)}
        out = np.asarray(sd.output(feeds, ["y"])["y"])
        with torch.no_grad():
            golden = model(*xs).numpy()
        if exact:
            np.testing.assert_array_equal(out, golden)
        else:
            np.testing.assert_allclose(out, golden, atol=1e-5, rtol=1e-5)

    def test_for_loop(self):
        self._match(_ForLoopNet(), [torch.randn(2, 3)])

    def test_while_loop_data_dependent(self):
        # INT64_MAX trip count + dynamic cond: 5 iterations at this input
        self._match(_WhileLoopNet(), [torch.ones(2, 3)])

    def test_if_both_branches(self):
        self._match(_CondNet(), [torch.randn(2, 3) + 5.0])
        self._match(_CondNet(), [torch.randn(2, 3) - 9.0])

    def test_greedy_decode_loop(self):
        torch.manual_seed(0)
        m = _GreedyDecode().eval()
        self._match(m, [torch.randint(0, 20, (2, 1)), torch.randn(2, 16)])

    def test_einsum(self):
        self._match(_EinsumNet(),
                    [torch.randn(2, 3, 4), torch.randn(2, 4, 5)],
                    scripted=False, exact=False)

    def test_op_tail(self):
        torch.manual_seed(1)
        self._match(_OpTailNet(), [torch.randn(3, 6)], scripted=False,
                    exact=False)

    def test_rule_count_floor(self):
        from deeplearning4j_tpu.imports.onnx_import import _ORULES

        assert len(_ORULES) >= 110, len(_ORULES)


class TestControlFlowSerialization:
    """Round-4: imported control-flow models SERIALIZE (structured
    __cf_* nodes carry their sub-graphs as specs — the closure-based
    custom_op path could not save). Reference parity: SameDiff .fb
    round-trips TFGraphMapper-imported control flow (path-cite)."""

    def test_greedy_decode_save_load_matches(self, tmp_path):
        torch.manual_seed(0)
        m = _GreedyDecode().eval()
        tok0 = torch.randint(0, 20, (2, 1))
        h0 = torch.randn(2, 16)
        data = _export_scripted(m, [tok0, h0])
        sd = import_onnx(data)
        feeds = {"x0": tok0.numpy(), "x1": h0.numpy()}
        ref = np.asarray(sd.output(feeds, ["y"])["y"])

        from deeplearning4j_tpu.samediff import SameDiff

        p = str(tmp_path / "greedy.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = np.asarray(sd2.output(feeds, ["y"])["y"])
        np.testing.assert_array_equal(out, ref)

    def test_while_loop_save_load_matches(self, tmp_path):
        m = _WhileLoopNet()
        x = torch.ones(2, 3)
        data = _export_scripted(m, [x])
        sd = import_onnx(data)
        ref = np.asarray(sd.output({"x0": x.numpy()}, ["y"])["y"])

        from deeplearning4j_tpu.samediff import SameDiff

        p = str(tmp_path / "while.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = np.asarray(sd2.output({"x0": x.numpy()}, ["y"])["y"])
        np.testing.assert_array_equal(out, ref)


class TestONNXDynamicBatch:
    """torch dynamic_axes exports (round 4): feed-forward architectures
    import once and run at ANY batch size (the Shape rule folds dynamic
    dims as -1 sentinels that resolve in Reshape targets); graphs that
    build runtime STATE shapes from a dynamic dim (torch RNN initial
    states) are rejected loudly at import instead of silently baking
    batch=1."""

    def _export_dynamic(self, model, x):
        from torch.onnx._internal.torchscript_exporter import (
            onnx_proto_utils,
        )

        orig = onnx_proto_utils._add_onnxscript_fn
        onnx_proto_utils._add_onnxscript_fn = lambda mb, co: mb
        try:
            buf = io.BytesIO()
            torch.onnx.export(
                model, (x,), buf, input_names=["x"], output_names=["y"],
                dynamic_axes={"x": {0: "batch"}, "y": {0: "batch"}},
                dynamo=False)
            return buf.getvalue()
        finally:
            onnx_proto_utils._add_onnxscript_fn = orig

    def test_resnet18_runs_at_two_batch_sizes(self):
        torch.manual_seed(0)
        m = _ResNet18().eval()
        sd = import_onnx(self._export_dynamic(m, torch.randn(2, 3, 64, 64)))
        for b in (2, 5):
            x = torch.randn(b, 3, 64, 64)
            out = np.asarray(sd.output({"x": x.numpy()}, ["y"])["y"])
            with torch.no_grad():
                golden = m(x).numpy()
            np.testing.assert_allclose(out, golden, atol=1e-4, rtol=1e-4)

    def test_rnn_state_from_dynamic_dim_rejected_loudly(self):
        torch.manual_seed(0)
        m = _LSTMSeq().eval()
        data = self._export_dynamic(m, torch.randint(0, 50, (2, 12)))
        with pytest.raises(NotImplementedError, match="dynamic dim"):
            import_onnx(data)

    def test_slice_end_from_dynamic_dim_rejected_loudly(self):
        """Round-5 regression (advisor repro): x[:x.shape[0]] exported with
        dynamic_axes folds the batch dim as the -1 sentinel, which reached
        Slice `ends` as a plain negative index and silently dropped the
        last row. const() now rejects sentinel-derived values for every
        consumer except Reshape."""

        class _SliceByShape(torch.nn.Module):
            def forward(self, x):
                return x[: x.shape[0]] + 1.0

        data = self._export_dynamic(
            _SliceByShape().eval(), torch.randn(2, 4))
        with pytest.raises(NotImplementedError, match="dynamic"):
            import_onnx(data)

    def test_static_dim_extracted_from_dynamic_shape_still_imports(self):
        """Provenance taint alone would over-reject: x.shape[1]//2 derives
        from the dynamic-batch Shape fold but its VALUE is static. The
        dependence probe (evaluate with two sentinel substitutions) keeps
        this importable while still rejecting true batch-dependence."""

        class _HalfSlice(torch.nn.Module):
            def forward(self, x):
                return x[:, : x.shape[1] // 2] * 2.0

        m = _HalfSlice().eval()
        sd = import_onnx(self._export_dynamic(m, torch.randn(2, 6)))
        for b in (2, 5):
            x = torch.randn(b, 6)
            out = np.asarray(sd.output({"x": x.numpy()}, ["y"])["y"])
            np.testing.assert_allclose(out, m(x).numpy(), atol=1e-6)

    def test_runtime_consumer_of_static_dim_imports(self):
        """Round-5 regression (review finding): when the static-extracted
        dim feeds RUNTIME arithmetic (Mul) instead of going through
        const(), the import-time output check used provenance only and
        wrongly rejected the graph. The refined check probes the
        static/runtime boundary value and keeps this importable."""

        class _ScaleByWidth(torch.nn.Module):
            def forward(self, x):
                return x * x.shape[1]

        m = _ScaleByWidth().eval()
        sd = import_onnx(self._export_dynamic(m, torch.randn(2, 6)))
        for b in (2, 4):
            x = torch.randn(b, 6)
            out = np.asarray(sd.output({"x": x.numpy()}, ["y"])["y"])
            np.testing.assert_allclose(out, m(x).numpy(), atol=1e-6)

    def test_runtime_consumer_of_batch_dim_still_rejected(self):
        """Counterpart: the BATCH dim's value reaching runtime arithmetic
        is genuinely batch-dependent — must stay a loud rejection, not a
        silent -1."""

        class _ScaleByBatch(torch.nn.Module):
            def forward(self, x):
                return x * x.shape[0]

        data = self._export_dynamic(_ScaleByBatch().eval(),
                                    torch.randn(2, 6))
        with pytest.raises(NotImplementedError, match="dynamic|sentinel"):
            import_onnx(data)


class TestTFDynamicBatch:
    @staticmethod
    def _freeze_dynamic(fn):
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        conc = tf.function(fn).get_concrete_function(
            tf.TensorSpec([None, 6], tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        in_name = frozen.inputs[0].name.split(":")[0]
        out_name = frozen.outputs[0].name
        return frozen.graph.as_graph_def(), frozen, in_name, out_name

    def test_shape_n_static_dim_imports_batch_dim_rejected(self, rng):
        """Round-5 regression (review finding): the ShapeN rule folded the
        dynamic batch dim as a -1 constant WITHOUT the Shape rule's taint,
        so batch-dependent values silently reached runtime arithmetic.
        ShapeN now taints like Shape: the static-dim consumer imports (and
        matches TF at two batch sizes), the batch-dim consumer fails
        loudly."""

        def uses_static_dim(x):
            s = tf.raw_ops.ShapeN(input=[x, x])[0]
            return x * tf.cast(s[1], tf.float32)

        gd, frozen, in_name, out_name = self._freeze_dynamic(uses_static_dim)
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_name]
        for b in (2, 5):
            x = rng.normal(size=(b, 6)).astype(np.float32)
            res = np.asarray(sd.output({in_name: x}, [key])[key])
            np.testing.assert_allclose(res, np.asarray(frozen(
                tf.constant(x))[0]), atol=1e-5)

        def uses_batch_dim(x):
            s = tf.raw_ops.ShapeN(input=[x, x])[0]
            return x * tf.cast(s[0], tf.float32)

        gd2, _, in2, out2 = self._freeze_dynamic(uses_batch_dim)
        with pytest.raises(NotImplementedError, match="dynamic|sentinel"):
            sd2 = import_graph_def(gd2)
            key2 = sd2.tf_name_map[out2]
            sd2.output({in2: np.zeros((2, 6), np.float32)}, [key2])

    def test_imported_graph_runs_at_two_batch_sizes(self, rng):
        """TF frozen graphs traced with batch=None import once and run at
        any batch size (the keras Pack/StridedSlice reshape pattern folds
        the dynamic dim as -1)."""
        tf.keras.utils.set_random_seed(7)
        model = tf.keras.applications.MobileNetV2(
            weights=None, include_top=False, input_shape=(64, 64, 3),
            pooling="avg")
        gd, frozen, in_name, out_name = _freeze_keras(model)
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_name]
        for b in (2, 5):
            x = rng.normal(size=(b, 64, 64, 3)).astype(np.float32)
            golden = frozen(tf.constant(x))
            if isinstance(golden, (list, tuple)):
                golden = golden[0]
            res = np.asarray(sd.output({in_name: x}, [key])[key])
            np.testing.assert_allclose(res, np.asarray(golden), atol=1e-4,
                                       rtol=1e-4)
