"""YOLOv2 output layer + detection decoding + zoo detection models.

Reference test parity: deeplearning4j-core objdetect tests
(Yolo2OutputLayer gradchecks/decoding; SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.objdetect import (
    DetectedObject,
    Yolo2OutputLayer,
    get_predicted_objects,
)

ANCHORS = ((1.0, 1.5), (3.0, 3.0))


def _labels(b=2, sy=4, sx=4, c=3):
    lab = np.zeros((b, sy, sx, 4 + c), np.float32)
    # one object in cell (1,2) of example 0: box from (2.1,1.2) to (3.3,2.0)
    lab[0, 1, 2, :4] = [2.1, 1.2, 3.3, 2.0]
    lab[0, 1, 2, 4 + 1] = 1.0
    return lab


class TestYoloLoss:
    def test_loss_finite_and_differentiable(self, rng):
        layer = Yolo2OutputLayer(anchors=ANCHORS)
        x = jnp.asarray(rng.normal(size=(2, 4, 4, 2 * 8)) * 0.1, jnp.float32)
        lab = jnp.asarray(_labels())

        def loss(x):
            return layer.compute_loss({}, {}, x, lab)

        val, grad = jax.value_and_grad(loss)(x)
        assert np.isfinite(float(val))
        assert np.isfinite(np.asarray(grad)).all()
        assert float(jnp.max(jnp.abs(grad))) > 0

    def test_training_reduces_loss(self, rng):
        layer = Yolo2OutputLayer(anchors=ANCHORS)
        lab = jnp.asarray(_labels())
        x = jnp.asarray(rng.normal(size=(2, 4, 4, 16)) * 0.1, jnp.float32)

        loss_fn = jax.jit(lambda x: layer.compute_loss({}, {}, x, lab))
        g = jax.jit(jax.grad(lambda x: layer.compute_loss({}, {}, x, lab)))
        l0 = float(loss_fn(x))
        for _ in range(200):
            x = x - 0.05 * g(x)
        assert float(loss_fn(x)) < l0 * 0.6

    def test_weighted_loss_ignores_padded(self, rng):
        layer = Yolo2OutputLayer(anchors=ANCHORS)
        x = jnp.asarray(rng.normal(size=(2, 4, 4, 16)), jnp.float32)
        lab = jnp.asarray(_labels())
        w_first = layer.compute_loss({}, {}, x, lab,
                                     weights=jnp.asarray([1.0, 0.0]))
        only_first = layer.compute_loss({}, {}, x[:1], lab[:1])
        np.testing.assert_allclose(float(w_first), float(only_first), rtol=1e-5)


class TestDecoding:
    def test_decode_and_nms(self):
        layer = Yolo2OutputLayer(anchors=ANCHORS)
        out = np.full((1, 4, 4, 16), -8.0, np.float32)  # conf sigmoid ≈ 0
        # confident detection in cell (1,2), anchor 0, class 2
        out[0, 1, 2, 0:5] = [0.0, 0.0, 0.0, 0.0, 8.0]
        out[0, 1, 2, 5:8] = [0.0, 0.0, 4.0]
        # duplicate overlapping detection with lower confidence, anchor 1
        out[0, 1, 2, 8:13] = [0.0, 0.0, -1.2, -0.8, 4.0]
        out[0, 1, 2, 13:16] = [0.0, 0.0, 3.0]
        dets = get_predicted_objects(layer, out, threshold=0.5,
                                     nms_threshold=0.4)[0]
        assert len(dets) >= 1
        d = dets[0]
        assert d.predicted_class == 2
        assert abs(d.center_x - 2.5) < 0.01 and abs(d.center_y - 1.5) < 0.01
        assert abs(d.width - 1.0) < 0.01 and abs(d.height - 1.5) < 0.01
        # the weaker overlapping box was suppressed
        assert all(o.confidence >= 0.9 for o in dets[:1])


@pytest.mark.slow
class TestDetectionZoo:
    def test_tiny_yolo_builds_and_steps(self, rng):
        from deeplearning4j_tpu.zoo import TinyYOLO

        net = TinyYOLO(input_shape=(64, 64, 3), num_classes=3).init()
        x = rng.normal(size=(2, 64, 64, 3)).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 2, 2, 5 * (5 + 3))
        lab = np.zeros((2, 2, 2, 4 + 3), np.float32)
        lab[0, 0, 1, :4] = [1.1, 0.2, 1.9, 0.9]
        lab[0, 0, 1, 4] = 1.0
        losses = []
        for _ in range(12):
            net._fit_batch(jnp.asarray(x), jnp.asarray(lab))
            losses.append(float(net.score_value))
        # training loss trend (eval-mode batchnorm stats lag this early)
        assert losses[-1] < losses[0], losses

    def test_inception_resnet_v1_builds(self, rng):
        from deeplearning4j_tpu.zoo import InceptionResNetV1

        net = InceptionResNetV1(input_shape=(96, 96, 3), num_classes=5).init()
        x = rng.normal(size=(1, 96, 96, 3)).astype(np.float32)
        out = net.output(x)
        assert np.asarray(out).shape == (1, 5)
