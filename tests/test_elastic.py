"""Elastic fault-tolerant runtime (docs/FAULT_TOLERANCE.md): RetryPolicy /
FaultInjector behavior, atomic + corruption-tolerant checkpoints,
checkpoint->resume bit-identity (MLN, CG, TBPTT, bucketed), and one test per
injected fault asserting its SPECIFIC recovery path fired — worker restart,
regroup, rollback, graceful drain. No recovery code ships unexercised."""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.listeners import TrainingListener
from deeplearning4j_tpu.nn.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import ElasticTrainer, FileMembership
from deeplearning4j_tpu.util import ShardedCheckpointer, telemetry as tm
from deeplearning4j_tpu.util.faults import (DROP_HEARTBEAT, INJECT_NAN,
                                            KILL_ETL_WORKER,
                                            STALL_PREFETCH, FaultInjector,
                                            RetryExhaustedError, RetryPolicy,
                                            get_injector, parse_fault_spec)

R = np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _clean_injector():
    get_injector().clear()
    yield
    get_injector().clear()
    # NaN-injection tests flip training.* checks in the PROCESS-GLOBAL
    # health registry; restore them so a later suite's /healthz assertion
    # (e.g. test_serving's 200 contract) sees a healthy process — the r17
    # hygiene convention for process-global check state
    _ok, checks = tm.get_telemetry().health_report()
    for name, c in checks.items():
        if name.startswith("training.") and not c.get("ok"):
            tm.set_health(name, True, "test cleanup (elastic NaN leg)")


def _counter(name):
    return tm.get_telemetry().snapshot()["counters"].get(name, 0.0)


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(bool(np.array_equal(np.asarray(x), np.asarray(y)))
               for x, y in zip(la, lb))


def _mln(seed=0, buckets=None, seq=None, tbptt=0, recurrent=False):
    b = NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
    if buckets is not None:
        b = b.batch_buckets(buckets)
    if seq is not None:
        b = b.seq_buckets(seq)
    if tbptt:
        b = b.tbptt_length(tbptt)
    lb = b.list()
    if recurrent:
        conf = (lb.layer(LSTM(n_in=6, n_out=8))
                .layer(RnnOutputLayer(n_in=8, n_out=3))
                .set_input_type(InputType.recurrent(6, 12)).build())
    else:
        conf = (lb.layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _cg(seed=3):
    g = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
         .graph_builder().add_inputs("in")
         .add_layer("d1", DenseLayer(n_in=4, n_out=6, activation="tanh"), "in")
         .add_layer("d2", DenseLayer(n_in=4, n_out=6, activation="relu"), "in")
         .add_layer("out", OutputLayer(n_in=12, n_out=2), "d1", "d2")
         .set_outputs("out").set_input_types((4,)).build())
    return ComputationGraph(g).init()


def _dense_iter(batch=8, n=32, f=4, c=2, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, f)).astype(np.float32)
    y = np.eye(c, dtype=np.float32)[rng.integers(0, c, n)]
    return lambda: ArrayDataSetIterator(x, y, batch=batch)


class _SigtermAt(TrainingListener):
    """Deliver a real SIGTERM to ourselves after iteration k completes —
    exactly what a preemption notice does to a training process."""

    def __init__(self, at_iteration):
        self.at_iteration = at_iteration

    def iteration_done(self, model, iteration, epoch):
        if iteration == self.at_iteration:
            os.kill(os.getpid(), signal.SIGTERM)


# ---------------------------------------------------------------------------
# RetryPolicy / FaultInjector
# ---------------------------------------------------------------------------
class TestRetryPolicy:
    def test_backoff_schedule_caps(self):
        p = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                        max_delay=0.3)
        assert p.delays() == [0.1, 0.2, 0.3, 0.3]
        assert RetryPolicy(max_attempts=1).delays() == []

    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        p = RetryPolicy(max_attempts=4, base_delay=0.001)
        before = _counter("elastic.retries_total{op=flaky}")
        assert p.run(flaky, name="flaky") == "ok"
        assert len(calls) == 3
        assert _counter("elastic.retries_total{op=flaky}") == before + 2

    def test_exhaustion_raises_with_cause(self):
        def always():
            raise ValueError("permanent")

        with pytest.raises(RetryExhaustedError, match="3 attempt"):
            RetryPolicy(max_attempts=3, base_delay=0.001).run(
                always, name="always")
        try:
            RetryPolicy(max_attempts=2, base_delay=0.001).run(
                always, name="always")
        except RetryExhaustedError as e:
            assert isinstance(e.__cause__, ValueError)

    def test_deadline_cuts_retries_short(self):
        t0 = time.monotonic()
        with pytest.raises(RetryExhaustedError, match="deadline"):
            RetryPolicy(max_attempts=10, base_delay=5.0,
                        deadline=0.01).run(
                lambda: (_ for _ in ()).throw(OSError("x")), name="slow")
        assert time.monotonic() - t0 < 1.0  # did NOT sleep the 5s backoff

    def test_non_retryable_passes_through(self):
        with pytest.raises(KeyError):
            RetryPolicy(max_attempts=3, base_delay=0.001).run(
                lambda: (_ for _ in ()).throw(KeyError("nope")),
                retry_on=(OSError,), name="typed")


class TestFaultInjector:
    def test_parse_env_spec(self):
        faults = parse_fault_spec(
            "kill_etl_worker, inject_nan@5, stall_prefetch:3.5")
        assert [(f.kind, f.at_step, f.arg) for f in faults] == [
            ("kill_etl_worker", None, None), ("inject_nan", 5, None),
            ("stall_prefetch", None, 3.5)]

    def test_parse_unknown_kind_is_loud(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            parse_fault_spec("kill_everything@1")

    def test_step_gate_on_stepless_kind_is_loud(self):
        # kill_etl_worker fires at a site with no step concept: @step would
        # arm a fault that can never fire — a chaos run that tests nothing
        with pytest.raises(ValueError, match="no step concept"):
            parse_fault_spec("kill_etl_worker@2")
        with pytest.raises(ValueError, match="no step concept"):
            get_injector().inject(STALL_PREFETCH, at_step=3)

    def test_step_gating_and_once_semantics(self):
        inj = get_injector()
        inj.inject(INJECT_NAN, at_step=5)
        assert inj.fire(INJECT_NAN, step=4) is None
        assert inj.fire(INJECT_NAN) is None  # step-gated, site has no step
        assert inj.fire(INJECT_NAN, step=6) is not None
        assert inj.fire(INJECT_NAN, step=7) is None  # consumed (count=1)
        assert inj.log == [(INJECT_NAN, 6)]

    def test_repeating_fault(self):
        inj = get_injector()
        inj.inject(STALL_PREFETCH, count=2)
        assert inj.fire(STALL_PREFETCH) is not None
        assert inj.fire(STALL_PREFETCH) is not None
        assert inj.fire(STALL_PREFETCH) is None


# ---------------------------------------------------------------------------
# Checkpoint atomicity / corruption tolerance
# ---------------------------------------------------------------------------
class TestCheckpointer:
    def _fit_and_save(self, tmp_path, steps=2):
        net = _mln(seed=0)
        x, y = (np.ones((8, 4), np.float32),
                np.eye(2, dtype=np.float32)[np.zeros(8, int)])
        ck = ShardedCheckpointer(str(tmp_path / "ck"), keep=3, log_fn=None)
        for _ in range(steps):
            net.fit(x, y, epochs=1)
            ck.save(net.iteration, net,
                    extra_meta={"batch_in_epoch": net.iteration % 2})
        return net, ck

    def test_tmp_orphan_invisible_and_swept(self, tmp_path):
        net, ck = self._fit_and_save(tmp_path)
        # a crash mid-save leaves exactly these; own-pid orphans sweep on
        # the next save, a foreign writer's only once stale (one-writer
        # contract: a LIVE concurrent write must survive the sweep)
        mine = os.path.join(ck.directory, f".tmp-999-{os.getpid()}")
        foreign_live = os.path.join(ck.directory, ".tmp-998-12345")
        foreign_stale = os.path.join(ck.directory, ".tmp-997-12345")
        for d in (mine, foreign_live, foreign_stale):
            os.makedirs(d)
        os.utime(foreign_stale, (time.time() - 7200, time.time() - 7200))
        assert all(s not in ck.all_steps() for s in (997, 998, 999))
        ck.save(net.iteration + 1, net)
        assert not os.path.exists(mine)
        assert os.path.exists(foreign_live)
        assert not os.path.exists(foreign_stale)

    def test_meta_sidecar_roundtrip(self, tmp_path):
        net, ck = self._fit_and_save(tmp_path)
        step = ck.latest_step()
        meta = ck.load_meta(step)
        assert meta["step"] == step
        assert "batch_in_epoch" in meta

    def test_corrupt_newest_skipped_with_warning(self, tmp_path):
        """Regression: truncate every file of the newest checkpoint
        mid-byte — restore must warn + skip to the older good one, never
        crash."""
        import glob

        net, ck = self._fit_and_save(tmp_path, steps=2)
        good_step = ck.all_steps()[0]
        good = MultiLayerNetwork(net.conf).init()
        ck.restore(good, step=good_step)
        newest = os.path.join(ck.directory, str(ck.latest_step()))
        for f in glob.glob(os.path.join(newest, "**", "*"), recursive=True):
            if os.path.isfile(f):
                with open(f, "r+b") as fh:
                    fh.truncate(max(0, os.path.getsize(f) // 3))
        warnings = []
        ck.log = warnings.append
        before = _counter("checkpoint.corrupt_skipped_total")
        net2 = MultiLayerNetwork(net.conf).init()
        assert ck.restore_latest_good(net2) == good_step
        assert _counter("checkpoint.corrupt_skipped_total") == before + 1
        assert warnings and "failed to load" in warnings[0]
        assert _leaves_equal(net2.params, good.params)

    def test_restore_latest_good_none_when_empty(self, tmp_path):
        ck = ShardedCheckpointer(str(tmp_path / "empty"), log_fn=None)
        assert ck.restore_latest_good(_mln()) is None

    def test_async_save_commits_identically(self, tmp_path):
        net, ck = self._fit_and_save(tmp_path)
        ck.save(net.iteration + 1, net, block=False)
        ck.wait_until_finished()
        sync_net = MultiLayerNetwork(net.conf).init()
        ck.restore(sync_net, step=net.iteration + 1)
        assert _leaves_equal(sync_net.params, net.params)
        assert _leaves_equal(sync_net.opt_states, net.opt_states)

    def test_rng_key_round_trips(self, tmp_path):
        net, ck = self._fit_and_save(tmp_path)
        key = np.asarray(net._rng_key).copy()
        net2 = MultiLayerNetwork(net.conf).init()
        ck.restore(net2)
        assert np.array_equal(np.asarray(net2._rng_key), key)


# ---------------------------------------------------------------------------
# Kill-and-resume bit-identity (acceptance: MLN + CG, TBPTT, bucketed)
# ---------------------------------------------------------------------------
class TestResumeBitIdentity:
    def _drain_and_resume(self, build, data_iter, tmp_path, epochs=3,
                          kill_at=5, checkpoint_every=2):
        """fit() interrupted by a real SIGTERM at step ``kill_at``, resumed
        from its auto-checkpoint in a FRESH model, must end bit-identical
        to an uninterrupted run of the same total step count."""
        ref = build()
        ref.fit(data_iter(), epochs=epochs)

        net = build()
        net.listeners.append(_SigtermAt(kill_at))
        t1 = ElasticTrainer(net, str(tmp_path / "ck"),
                            checkpoint_every=checkpoint_every, log_fn=None)
        t1.fit(data_iter(), epochs=epochs)
        assert t1.drained and net.iteration == kill_at
        assert t1.ckpt.latest_step() == kill_at  # drain checkpointed

        net2 = build()
        t2 = ElasticTrainer(net2, str(tmp_path / "ck"),
                            checkpoint_every=checkpoint_every, log_fn=None)
        t2.fit(data_iter(), epochs=epochs)
        assert t2.resumed_from == kill_at
        assert t2.state == "completed"
        assert net2.iteration == ref.iteration
        assert net2.epoch == ref.epoch
        assert _leaves_equal(net2.params, ref.params)
        assert _leaves_equal(net2.opt_states, ref.opt_states)
        assert np.array_equal(np.asarray(net2._rng_key),
                              np.asarray(ref._rng_key))

    def test_mln_sigterm_resume_bit_identical(self, tmp_path):
        self._drain_and_resume(_mln, _dense_iter(), tmp_path)

    def test_cg_sigterm_resume_bit_identical(self, tmp_path):
        self._drain_and_resume(_cg, _dense_iter(), tmp_path)

    def test_mln_tbptt_resume_bit_identical(self, tmp_path):
        def data():
            rng = np.random.default_rng(1)
            x = rng.standard_normal((8, 12, 6)).astype(np.float32)
            y = rng.standard_normal((8, 12, 3)).astype(np.float32)
            return ArrayDataSetIterator(x, y, batch=4)

        # tbptt_length 4 over T=12: 3 segments (= iterations) per batch
        self._drain_and_resume(
            lambda: _mln(seed=5, tbptt=4, recurrent=True), data, tmp_path,
            epochs=2, kill_at=6, checkpoint_every=3)

    def test_mln_bucketed_resume_bit_identical(self, tmp_path):
        def data():
            rng = np.random.default_rng(2)
            x = rng.standard_normal((21, 4)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 21)]
            # batch 6 over 21 rows: ragged tail pads to the 8-bucket with
            # 0/1 weights — the padded path must resume bit-identically too
            return ArrayDataSetIterator(x, y, batch=6)

        self._drain_and_resume(
            lambda: _mln(seed=6, buckets=(8,)), data, tmp_path,
            epochs=3, kill_at=5, checkpoint_every=2)


# ---------------------------------------------------------------------------
# Every injected fault -> its specific recovery path
# ---------------------------------------------------------------------------
class TestFaultRecoveryPaths:
    def test_kill_etl_worker_restarts_only_that_chunk(self):
        from deeplearning4j_tpu.datavec.executor import (
            MultiProcessTransformExecutor)
        from deeplearning4j_tpu.datavec.transform import (Schema,
                                                          TransformProcess)

        schema = Schema.builder().add_column_double("x").build()
        tp = (TransformProcess.builder(schema)
              .double_column_transform("x", _slow_double).build())
        records = [[float(i)] for i in range(512)]
        serial = tp.execute(records)
        get_injector().inject(KILL_ETL_WORKER)
        before = _counter("etl.worker_restarts_total")
        ex = MultiProcessTransformExecutor(tp, num_workers=4,
                                           min_records_per_worker=64,
                                           timeout=60)
        out = ex.execute(records)
        assert out == serial  # bit-identical in-order merge, kill included
        assert _counter("etl.worker_restarts_total") >= before + 1

    def test_etl_retries_exhausted_is_loud(self):
        from deeplearning4j_tpu.datavec.executor import (
            MultiProcessTransformExecutor, TransformExecutionError)
        from deeplearning4j_tpu.datavec.transform import (Schema,
                                                          TransformProcess)

        schema = Schema.builder().add_column_double("x").build()
        tp = (TransformProcess.builder(schema)
              .double_column_transform("x", _always_boom).build())
        ex = MultiProcessTransformExecutor(tp, num_workers=2,
                                           min_records_per_worker=64,
                                           timeout=30)
        with pytest.raises(TransformExecutionError,
                           match=r"failed after 3 attempt"):
            ex.execute([[float(i)] for i in range(256)])

    def test_stall_prefetch_diagnostics_and_counter(self):
        from deeplearning4j_tpu.data.prefetch import (AsyncDataSetIterator,
                                                      PrefetchStalledError)

        x = np.zeros((16, 4), np.float32)
        y = np.zeros((16, 2), np.float32)
        get_injector().inject(STALL_PREFETCH, arg=30.0)
        it = AsyncDataSetIterator(ArrayDataSetIterator(x, y, batch=4),
                                  timeout=0.5, device_put=False)
        before = _counter("prefetch.stall_timeouts_total")
        with pytest.raises(PrefetchStalledError) as ei:
            list(it)
        msg = str(ei.value)
        # the post-mortem payload: depth, cursor, producer liveness
        assert "queue depth" in msg
        assert "last successful batch index" in msg
        assert "alive" in msg or "DEAD" in msg
        assert _counter("prefetch.stall_timeouts_total") == before + 1

    def test_inject_nan_rolls_back_and_completes(self, tmp_path):
        data = _dense_iter()
        ref = _mln()
        ref.fit(data(), epochs=3)

        net = _mln()
        get_injector().inject(INJECT_NAN, at_step=6)
        before = _counter("elastic.rollbacks_total")
        tr = ElasticTrainer(net, str(tmp_path / "ck"), checkpoint_every=3,
                            log_fn=None)
        tr.fit(data(), epochs=3)
        assert tr.rollbacks == 1
        assert _counter("elastic.rollbacks_total") == before + 1
        assert tr.state == "completed"
        # the poisoned step was rolled back and replayed clean: the final
        # params are bit-identical to the run that never saw the NaN
        assert _leaves_equal(net.params, ref.params)

    def test_inject_nan_rollback_under_coalesced_dispatch(self, tmp_path):
        """sync_every>1: the poisoned step's loss is detected at a WINDOW
        boundary (possibly the epoch-end flush), and checkpoints flush the
        dispatcher first so a NaN window can never be committed as a good
        rollback target."""
        def build():
            conf = (NeuralNetConfiguration.builder().seed(4)
                    .updater(Adam(1e-2)).sync_every(3).list()
                    .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                    .layer(OutputLayer(n_in=8, n_out=2))
                    .set_input_type(InputType.feed_forward(4)).build())
            return MultiLayerNetwork(conf).init()

        data = _dense_iter()
        ref = build()
        ref.fit(data(), epochs=3)
        net = build()
        get_injector().inject(INJECT_NAN, at_step=6)
        tr = ElasticTrainer(net, str(tmp_path / "ck"), checkpoint_every=4,
                            log_fn=None)
        tr.fit(data(), epochs=3)
        assert tr.rollbacks == 1 and tr.state == "completed"
        assert _leaves_equal(net.params, ref.params)

    def test_rollback_budget_exhausts_loudly(self, tmp_path):
        net = _mln()
        get_injector().inject(INJECT_NAN, at_step=2, count=-1)  # every step
        tr = ElasticTrainer(net, str(tmp_path / "ck"), checkpoint_every=2,
                            max_rollbacks=2, log_fn=None)
        with pytest.raises(RuntimeError, match="rollback budget exhausted"):
            tr.fit(_dense_iter()(), epochs=2)
        assert tr.rollbacks == 2
        assert tr.state == "failed"

    def test_drop_heartbeat_shrinks_world_at_regroup(self, tmp_path):
        d = str(tmp_path / "members")
        # b gets a PRIVATE injector so drop_heartbeat hits exactly ITS beat
        # thread (both members live in this one test process)
        b_injector = FaultInjector()
        b_injector.clear()
        a = FileMembership(d, process_id=0, world_size=2,
                           heartbeat_interval=0.05, miss_threshold=3,
                           barrier_timeout=20.0, log_fn=None)
        b = FileMembership(d, process_id=1, world_size=2,
                           heartbeat_interval=0.05, miss_threshold=3,
                           barrier_timeout=20.0, injector=b_injector,
                           log_fn=None)
        a.start()
        b.start()
        try:
            import threading

            views = {}
            tb = threading.Thread(
                target=lambda: views.setdefault(1, b.regroup(0)))
            tb.start()
            views[0] = a.regroup(0)
            tb.join(timeout=20)
            assert views[0].world == 2 and views[1].world == 2

            # b's heartbeats drop (the fault fires in ITS beat thread);
            # after the miss threshold, a's next regroup evicts it
            before = _counter("elastic.heartbeats_dropped_total")
            b_injector.inject(DROP_HEARTBEAT, arg=1000)
            deadline = time.monotonic() + 10
            while (_counter("elastic.heartbeats_dropped_total") <= before
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            time.sleep(0.05 * 4)  # past the freshness window
            view = a.regroup(1)
            assert view.world == 1 and view.members == (0,)
            assert a.regroups == 1
            assert _counter("elastic.regroups_total") >= 1
        finally:
            a.stop()
            b.stop()

    def test_sigkill_host_survivor_regroups_and_finishes(self, tmp_path):
        """ISSUE acceptance: 2 OS processes, one SIGKILLed mid-epoch; the
        survivor notices the missed heartbeats, regroups to world 1,
        re-shards the batches, and finishes all epochs."""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        env.pop("XLA_FLAGS", None)
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_dist_worker.py")
        d = str(tmp_path / "pod")
        procs = [subprocess.Popen(
            [sys.executable, worker, "--elastic", d, str(pid), "2"]
            + (["2"] if pid == 1 else []),  # pid 1 SIGKILLs itself at step 2
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        out0, err0 = procs[0].communicate(timeout=240)
        out1, _ = procs[1].communicate(timeout=240)
        assert procs[1].returncode == -signal.SIGKILL  # died hard, no JSON
        assert not out1.strip()
        assert procs[0].returncode == 0, err0[-1500:]
        r = json.loads([l for l in out0.splitlines()
                        if l.startswith("{")][-1])
        assert r["state"] == "completed"
        assert r["world_final"] == 1 and r["members_final"] == [0]
        assert r["regroups"] >= 1
        assert r["epoch"] == 3 and r["score_finite"]
        # 8 batches/epoch: epoch 0 sharded 2 ways (4 steps), then re-sharded
        # to all 8 for the remaining epochs
        assert r["iteration"] == 4 + 8 + 8

    def test_sigkill_with_grad_compression_migrates_residual(self, tmp_path):
        """Elastic × compression (ISSUE 10 satellite): same 2-process
        SIGKILL scenario, but the data plane is the COMPRESSED
        ParallelWrapper step — the survivor regroups with its
        error-feedback residual/threshold migrated through reshard (the
        iteration trace proves it kept training), and the final checkpoint
        carries the residual EXACTLY (bit-compared in-process against a
        fresh restore)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        env.pop("XLA_FLAGS", None)
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_dist_worker.py")
        d = str(tmp_path / "pod")
        procs = [subprocess.Popen(
            [sys.executable, worker, "--elastic-compress", d, str(pid), "2"]
            + (["2"] if pid == 1 else []),  # pid 1 SIGKILLs itself at step 2
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        out0, err0 = procs[0].communicate(timeout=240)
        out1, _ = procs[1].communicate(timeout=240)
        assert procs[1].returncode == -signal.SIGKILL
        assert not out1.strip()
        assert procs[0].returncode == 0, err0[-1500:]
        r = json.loads([l for l in out0.splitlines()
                        if l.startswith("{")][-1])
        assert r["state"] == "completed"
        assert r["world_final"] == 1 and r["members_final"] == [0]
        assert r["regroups"] >= 1
        assert r["epoch"] == 3 and r["score_finite"]
        assert r["iteration"] == 4 + 8 + 8  # same trace as the plain leg
        assert r["residual_exact"], r  # checkpoint carried the residual
        assert r["wire_bytes"] and r["wire_bytes"] > 0
        assert r["threshold"] and r["threshold"] > 0

    @pytest.mark.slow
    def test_sigkill_with_pipelined_trainer_restores_stacked_state(
            self, tmp_path):
        """Elastic × pipeline (ISSUE 14 satellite): the 2-process SIGKILL
        scenario with the PIPELINED trainer as the data plane — stacked
        stage params/optimizer state, GPipe microbatch schedule, lane DP.
        The survivor regroups and keeps training (same 4+8+8 iteration
        trace as the plain legs — reshard() migrated the stacked state
        through model layout bit-exactly), and the final checkpoint
        restores the STACKED stage state bit-exactly at the boundary
        (compared in-process against the live trainer's placed leaves)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        env.pop("XLA_FLAGS", None)
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_dist_worker.py")
        d = str(tmp_path / "pod")
        procs = [subprocess.Popen(
            [sys.executable, worker, "--pipe", d, str(pid), "2"]
            + (["2"] if pid == 1 else []),  # pid 1 SIGKILLs itself at step 2
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        out0, err0 = procs[0].communicate(timeout=240)
        out1, _ = procs[1].communicate(timeout=240)
        assert procs[1].returncode == -signal.SIGKILL
        assert not out1.strip()
        assert procs[0].returncode == 0, err0[-1500:]
        r = json.loads([l for l in out0.splitlines()
                        if l.startswith("{")][-1])
        assert r["state"] == "completed"
        assert r["world_final"] == 1 and r["members_final"] == [0]
        assert r["regroups"] >= 1
        assert r["epoch"] == 3 and r["score_finite"]
        assert r["iteration"] == 4 + 8 + 8  # same trace as the plain leg
        assert r["stacked_exact"], r  # checkpoint carried the stacked state
        assert r["pipe_stages"] == 2
        assert 0 < r["bubble_fraction"] < 1


def _slow_double(v):
    time.sleep(0.005)  # keep workers alive long enough to be killed
    return v * 2.0


def _always_boom(v):
    raise ValueError("deterministic child failure")


# ---------------------------------------------------------------------------
# Drain semantics + status surfaces
# ---------------------------------------------------------------------------
class TestDrainAndSurfaces:
    def test_sigterm_drains_gracefully(self, tmp_path):
        net = _mln()
        net.listeners.append(_SigtermAt(4))
        before = _counter("elastic.drains_total")
        tr = ElasticTrainer(net, str(tmp_path / "ck"), checkpoint_every=10,
                            log_fn=None)
        tr.fit(_dense_iter()(), epochs=3)
        assert tr.drained and tr.state == "drained"
        assert net.iteration == 4  # finished the in-flight step, no more
        assert tr.ckpt.latest_step() == 4  # work saved before leaving
        assert _counter("elastic.drains_total") == before + 1
        ok, checks = tm.get_telemetry().health_report()
        assert checks["elastic.drained"]["ok"]

    def test_healthz_has_elastic_membership_section(self, tmp_path):
        from deeplearning4j_tpu.util.ui_server import UIServer

        net = _mln()
        tr = ElasticTrainer(net, str(tmp_path / "ck"), checkpoint_every=50,
                            log_fn=None)
        tr.fit(_dense_iter()(), epochs=1)
        body, ok = UIServer._healthz()
        payload = json.loads(body)
        section = payload.get("elastic", {})
        assert section, "healthz must carry the elastic membership section"
        st = list(section.values())[-1]
        assert st["state"] == "completed"
        assert st["membership"]["world"] == 1
        assert st["last_checkpoint_step"] == net.iteration
        # scrape-time gauges ride the default collectors
        text = tm.install_default_collectors().prometheus_text()
        assert "dl4j_elastic_world_size" in text

    def test_parallel_wrapper_supervised_bit_identical(self, tmp_path):
        from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMesh

        n_dev = min(2, len(jax.devices()))
        mesh = lambda: TrainingMesh(  # noqa: E731
            data=n_dev, devices=jax.devices()[:n_dev])
        data = _dense_iter(batch=8)

        ref = _mln(seed=9)
        ParallelWrapper(ref, mesh=mesh()).fit(data(), epochs=2)

        net = _mln(seed=9)
        pw = ParallelWrapper(net, mesh=mesh())
        tr = ElasticTrainer(pw, str(tmp_path / "ck"), checkpoint_every=3,
                            log_fn=None)
        tr.fit(data(), epochs=2)
        assert tr.state == "completed"
        assert net.iteration == ref.iteration
        assert _leaves_equal(net.params, ref.params)
