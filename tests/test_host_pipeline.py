"""Async host-pipeline tests (ISSUE 2): multiprocess TransformProcess
executor, device-prefetch iterator, and sync-free (coalesced) listener
orchestration.

Invariants under test, per the acceptance criteria:
- multiprocess executor output is BIT-IDENTICAL to single-process on a CSV
  corpus (including order under record-dropping filters);
- prefetch staging of batch k+1 never mutates batch k's buffers (donation
  safety — the train step donates params/opt state, never batch arrays, and
  device_put allocates fresh buffers);
- a worker exception propagates to fit() (timeout + re-raise) instead of
  hanging the queue;
- sync_every > 1 training is loss-trajectory-equivalent to sync_every = 1
  (same final params, fixed seed), and listeners still receive EVERY
  iteration's scalars — just coalesced, already materialized.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    DataSet,
    PrefetchStalledError,
)
from deeplearning4j_tpu.datavec import (
    CSVRecordReader,
    MultiProcessTransformExecutor,
    ParallelTransformRecordReader,
    RecordReaderDataSetIterator,
    Schema,
    TransformExecutionError,
    TransformProcess,
    TransformProcessRecordReader,
)
from deeplearning4j_tpu.nn import (
    InputType,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam


# --------------------------------------------------------------------------
# multiprocess TransformProcess executor
# --------------------------------------------------------------------------

@pytest.fixture
def iris_csv(tmp_path):
    p = tmp_path / "iris.csv"
    rng = np.random.default_rng(0)
    lines = []
    for i in range(120):
        f = rng.uniform(0, 8, 4)
        lines.append(",".join(f"{v:.2f}" for v in f) + f",{i % 3}")
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _iris_schema():
    return (
        Schema.builder()
        .add_column_double("sl").add_column_double("sw")
        .add_column_double("pl").add_column_double("pw")
        .add_column_integer("label")
        .build()
    )


def _iris_tp():
    """Arithmetic + a record-dropping filter: order preservation under
    drops is exactly what the contiguous-chunk merge must get right."""
    return (
        TransformProcess.builder(_iris_schema())
        .double_column_transform("sl", lambda v: v * 2.0 + 0.25)
        .filter(lambda r, schema: float(r[1]) > 6.0)  # drop ~25% of records
        .double_column_transform("pw", lambda v: v - 1.0)
        .build()
    )


def test_mp_executor_bit_identical_to_serial(iris_csv):
    records = list(CSVRecordReader(iris_csv))
    tp = _iris_tp()
    serial = tp.execute(records)
    for workers in (2, 4):
        ex = MultiProcessTransformExecutor(
            tp, num_workers=workers, min_records_per_worker=1)
        assert ex.execute(records) == serial  # exact, order included


def test_mp_executor_small_input_serial_path(iris_csv):
    # below 2*min_records_per_worker the serial path runs — still identical
    records = list(CSVRecordReader(iris_csv))[:10]
    tp = _iris_tp()
    ex = MultiProcessTransformExecutor(tp, num_workers=4,
                                       min_records_per_worker=64)
    assert ex.execute(records) == tp.execute(records)


def test_mp_executor_worker_exception_propagates(iris_csv):
    records = list(CSVRecordReader(iris_csv))

    def boom(v):
        if v > 7.0:
            raise ValueError("bad record in worker")
        return v

    tp = (TransformProcess.builder(_iris_schema())
          .double_column_transform("sl", boom).build())
    ex = MultiProcessTransformExecutor(tp, num_workers=2,
                                       min_records_per_worker=1)
    with pytest.raises(TransformExecutionError, match="bad record in worker"):
        ex.execute(records)


def test_mp_executor_timeout_no_hang(iris_csv):
    records = list(CSVRecordReader(iris_csv))

    def wedge(v):
        time.sleep(60.0)
        return v

    tp = (TransformProcess.builder(_iris_schema())
          .double_column_transform("sl", wedge).build())
    ex = MultiProcessTransformExecutor(tp, num_workers=2, timeout=1.0,
                                       min_records_per_worker=1)
    t0 = time.perf_counter()
    with pytest.raises(TransformExecutionError, match="timed out"):
        ex.execute(records)
    assert time.perf_counter() - t0 < 30.0  # raised, not wedged


def test_parallel_record_reader_bridges_to_iterator(iris_csv):
    """ParallelTransformRecordReader drop-in where TransformProcessRecordReader
    goes: the DataSetIterator batches must match bit-for-bit."""
    tp = _iris_tp()
    base = TransformProcessRecordReader(CSVRecordReader(iris_csv), tp)
    par = ParallelTransformRecordReader(CSVRecordReader(iris_csv), tp,
                                        num_workers=2)
    it_serial = RecordReaderDataSetIterator(base, 16, label_index=4,
                                            num_classes=3)
    it_par = RecordReaderDataSetIterator(par, 16, label_index=4,
                                         num_classes=3)
    ds_s = list(it_serial)
    ds_p = list(it_par)
    assert len(ds_s) == len(ds_p) > 0
    for a, b in zip(ds_s, ds_p):
        np.testing.assert_array_equal(np.asarray(a.features),
                                      np.asarray(b.features))
        np.testing.assert_array_equal(np.asarray(a.labels),
                                      np.asarray(b.labels))


# --------------------------------------------------------------------------
# device-prefetch iterator
# --------------------------------------------------------------------------

def _batches(n=6, batch=4, feat=3, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(batch, feat)).astype(np.float32),
                    np.eye(2, dtype=np.float32)[rng.integers(0, 2, batch)])
            for _ in range(n)]


class _ListIterator:
    def __init__(self, batches):
        self.batches = batches

    def __iter__(self):
        return iter(self.batches)

    def reset(self):
        pass

    def batch_size(self):
        return len(self.batches[0].features)


def test_prefetch_batch_size_over_attribute_style_base(iris_csv):
    """RecordReaderDataSetIterator stores batch_size as an int ATTRIBUTE
    (shadowing the DataSetIterator method); the wrapper must handle both."""
    base = RecordReaderDataSetIterator(
        TransformProcessRecordReader(CSVRecordReader(iris_csv), _iris_tp()),
        16, label_index=4, num_classes=3)
    assert AsyncDataSetIterator(base).batch_size() == 16
    assert AsyncDataSetIterator(_ListIterator(_batches(2))).batch_size() == 4


def test_prefetch_yields_all_batches_in_order():
    src = _batches(8)
    out = list(AsyncDataSetIterator(_ListIterator(src), buffer_size=2))
    assert len(out) == 8
    for a, b in zip(src, out):
        np.testing.assert_array_equal(np.asarray(a.features),
                                      np.asarray(b.features))


def test_prefetch_stages_on_device():
    it = AsyncDataSetIterator(_ListIterator(_batches(3)), buffer_size=2)
    for ds in it:
        # staged arrays are device-resident jax Arrays, not host numpy
        assert hasattr(ds.features, "devices")
        assert hasattr(ds.labels, "devices")


def test_prefetch_donation_safety():
    """Batch k's buffers must not be touched by the in-flight device_put of
    batch k+1: hold every received batch, snapshot on receipt, let the
    worker run ahead, then verify all snapshots still match."""
    src = _batches(8)
    it = AsyncDataSetIterator(_ListIterator(src), buffer_size=2)
    held, snaps = [], []
    for ds in it:
        held.append(ds)
        snaps.append((np.asarray(ds.features).copy(),
                      np.asarray(ds.labels).copy()))
        time.sleep(0.01)  # worker stages k+1 (and k+2) while k is "computing"
    assert len(held) == 8
    seen = set()
    for src_ds, ds, (fx, fy) in zip(src, held, snaps):
        # fresh buffers, not aliases of each other...
        assert id(ds.features) not in seen
        seen.add(id(ds.features))
        # ...and still exactly the source batch after the pipeline drained
        np.testing.assert_array_equal(np.asarray(ds.features), fx)
        np.testing.assert_array_equal(np.asarray(ds.labels), fy)
        np.testing.assert_array_equal(np.asarray(src_ds.features), fx)


class _BoomIterator(_ListIterator):
    def __init__(self, batches, fail_after):
        super().__init__(batches)
        self.fail_after = fail_after

    def __iter__(self):
        for i, ds in enumerate(self.batches):
            if i == self.fail_after:
                raise RuntimeError("ETL worker exploded")
            yield ds


def test_prefetch_worker_exception_reraises():
    it = AsyncDataSetIterator(_BoomIterator(_batches(6), fail_after=2),
                              buffer_size=2)
    got = []
    with pytest.raises(RuntimeError, match="ETL worker exploded"):
        for ds in it:
            got.append(ds)
    assert len(got) == 2  # the good batches arrived first


def test_prefetch_worker_exception_propagates_to_fit():
    net = _lenet(seed=3, sync_every=2)
    x = np.random.default_rng(0).normal(size=(4, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[[1, 2, 3, 4]]
    batches = [DataSet(x, y) for _ in range(5)]
    it = AsyncDataSetIterator(_BoomIterator(batches, fail_after=3),
                              buffer_size=2)
    with pytest.raises(RuntimeError, match="ETL worker exploded"):
        net.fit(it, epochs=1)


class _WedgedIterator(_ListIterator):
    def __iter__(self):
        yield self.batches[0]
        threading.Event().wait(60.0)  # daemon worker; abandoned on timeout


def test_prefetch_stalled_worker_times_out():
    it = AsyncDataSetIterator(_WedgedIterator(_batches(2)), buffer_size=2,
                              timeout=0.5)
    t0 = time.perf_counter()
    with pytest.raises(PrefetchStalledError, match="no batch for 0.5s"):
        list(it)
    assert time.perf_counter() - t0 < 30.0


# --------------------------------------------------------------------------
# sync-free (coalesced) step orchestration
# --------------------------------------------------------------------------

def _lenet(seed=0, sync_every=1):
    conf = (
        NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
        .sync_every(sync_every).list()
        .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5),
                                padding="VALID", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(ConvolutionLayer(n_out=16, kernel_size=(5, 5),
                                padding="VALID", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2)))
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=10))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class _RecordingListener:
    def __init__(self):
        self.calls = []  # (iteration, epoch, score)

    def iteration_done(self, model, iteration, epoch):
        self.calls.append((iteration, epoch, model.score_value))


def _mnist_like(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    return x, y


@pytest.mark.slow
def test_sync_every_param_trajectory_equivalent():
    """sync_every only changes WHEN the host observes the loss, never the
    math: fixed-seed LeNet runs must land on bit-identical final params."""
    import jax

    x, y = _mnist_like(32)
    data = lambda: ArrayDataSetIterator(x, y, batch=8)  # noqa: E731
    net1 = _lenet(seed=7, sync_every=1)
    net1.set_listeners(_RecordingListener())
    net1.fit(data(), epochs=2)
    net4 = _lenet(seed=7, sync_every=4)
    net4.set_listeners(_RecordingListener())
    net4.fit(data(), epochs=2)
    for p1, p4 in zip(net1.params, net4.params):
        for l1, l4 in zip(jax.tree_util.tree_leaves(p1),
                          jax.tree_util.tree_leaves(p4)):
            np.testing.assert_array_equal(np.asarray(l1), np.asarray(l4))


@pytest.mark.slow
def test_sync_every_listeners_see_every_iteration_coalesced():
    x, y = _mnist_like(24)
    rec1, rec3 = _RecordingListener(), _RecordingListener()

    net1 = _lenet(seed=11, sync_every=1)
    net1.set_listeners(rec1)
    net1.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)

    net3 = _lenet(seed=11, sync_every=3)
    net3.set_listeners(rec3)
    counts = []
    for ds in ArrayDataSetIterator(x, y, batch=8):
        net3._fit_batch(np.asarray(ds.features), np.asarray(ds.labels))
        counts.append(len(rec3.calls))
    # 3 batches/epoch with window 3: nothing observed until the window fills
    assert counts == [0, 0, 3]
    net3._end_epoch()
    for ds in ArrayDataSetIterator(x, y, batch=8):
        net3._fit_batch(np.asarray(ds.features), np.asarray(ds.labels))
    net3._end_epoch()

    # every iteration's scalar arrived, in order, already materialized...
    assert [(c[0], c[1]) for c in rec3.calls] == \
        [(c[0], c[1]) for c in rec1.calls]
    assert all(isinstance(c[2], float) for c in rec3.calls)
    # ...and with the same values the per-step sync cadence observed
    np.testing.assert_allclose([c[2] for c in rec3.calls],
                               [c[2] for c in rec1.calls], rtol=1e-6)


def test_sync_every_flushes_at_epoch_end():
    """A window mid-fill at epoch end must flush so on_epoch_end callbacks
    observe a complete epoch (sync_every larger than batches/epoch)."""
    x, y = _mnist_like(16, seed=2)
    rec = _RecordingListener()
    net = _lenet(seed=5, sync_every=100)
    net.set_listeners(rec)
    net.fit(ArrayDataSetIterator(x, y, batch=8), epochs=1)
    assert [(c[0], c[1]) for c in rec.calls] == [(1, 0), (2, 0)]


def test_sync_every_validation_and_json_round_trip():
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    with pytest.raises(ValueError, match="sync_every"):
        NeuralNetConfiguration.builder().sync_every(0)
    conf = (NeuralNetConfiguration.builder().seed(1).sync_every(6).list()
            .layer(DenseLayer(n_in=4, n_out=2)).layer(OutputLayer(n_out=2))
            .build())
    assert conf.sync_every == 6
    rt = MultiLayerConfiguration.from_json(conf.to_json())
    assert rt.sync_every == 6
    # legacy JSON without the field defaults to the per-step cadence
    import json as _json
    d = _json.loads(conf.to_json())
    del d["sync_every"]
    assert MultiLayerConfiguration.from_json(_json.dumps(d)).sync_every == 1


def _graph_conf(sync_every):
    return (
        NeuralNetConfiguration.builder().seed(2).updater(Adam(0.01))
        .sync_every(sync_every)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_in=8, n_out=3), "d")
        .set_outputs("out")
        .set_input_types(InputType.feed_forward(4))
        .build()
    )


def test_sync_every_graph_json_round_trip():
    from deeplearning4j_tpu.nn.computation_graph import (
        ComputationGraphConfiguration,
    )

    conf = _graph_conf(5)
    assert conf.sync_every == 5
    rt = ComputationGraphConfiguration.from_json(conf.to_json())
    assert rt.sync_every == 5


def test_sync_every_graph_fit_equivalent_and_coalesced():
    """Same invariants on the ComputationGraph fit path: bit-equal params
    and the full per-iteration scalar stream under coalesced dispatch."""
    import jax

    from deeplearning4j_tpu.nn.computation_graph import ComputationGraph

    rng = np.random.default_rng(0)
    x = rng.normal(size=(24, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 24)]
    nets, recs = [], []
    for se in (1, 3):
        net = ComputationGraph(_graph_conf(se)).init()
        rec = _RecordingListener()
        net.listeners.append(rec)
        net.fit(ArrayDataSetIterator(x, y, batch=4), epochs=2)
        nets.append(net)
        recs.append(rec)
    assert [c[:2] for c in recs[1].calls] == [c[:2] for c in recs[0].calls]
    np.testing.assert_allclose([c[2] for c in recs[1].calls],
                               [c[2] for c in recs[0].calls], rtol=1e-6)
    for pa, pb in zip(nets[0].params.values(), nets[1].params.values()):
        for la, lb in zip(jax.tree_util.tree_leaves(pa),
                          jax.tree_util.tree_leaves(pb)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_sync_every_env_default(monkeypatch):
    from deeplearning4j_tpu.config import Environment

    monkeypatch.setenv("DL4J_TPU_SYNC_EVERY", "8")
    env = Environment()
    assert env.default_sync_every == 8
    monkeypatch.setenv("DL4J_TPU_SYNC_EVERY", "0")
    with pytest.raises(ValueError, match="DL4J_TPU_SYNC_EVERY"):
        Environment()
