"""Evaluation metric tests vs hand-computed values (Evaluation.java test parity)."""

import numpy as np
import pytest

from deeplearning4j_tpu.eval import Evaluation, ROC, RegressionEvaluation


def test_evaluation_perfect():
    ev = Evaluation()
    labels = np.eye(3)[[0, 1, 2, 0]]
    ev.eval(labels, labels)
    assert ev.accuracy() == 1.0
    assert ev.precision() == 1.0
    assert ev.recall() == 1.0
    assert ev.f1() == 1.0


def test_evaluation_known_confusion():
    ev = Evaluation()
    true_idx = [0, 0, 1, 1, 1, 2]
    pred_idx = [0, 1, 1, 1, 0, 2]
    ev.eval(np.eye(3)[true_idx], np.eye(3)[pred_idx])
    cm = ev.confusion_matrix()
    np.testing.assert_array_equal(cm, [[1, 1, 0], [1, 2, 0], [0, 0, 1]])
    assert ev.accuracy() == pytest.approx(4 / 6)
    # class 0: precision 1/2, recall 1/2
    assert ev.precision(0) == pytest.approx(0.5)
    assert ev.recall(0) == pytest.approx(0.5)
    # class 1: precision 2/3, recall 2/3
    assert ev.precision(1) == pytest.approx(2 / 3)


def test_evaluation_incremental_batches():
    ev = Evaluation()
    ev.eval(np.eye(2)[[0, 1]], np.eye(2)[[0, 1]])
    ev.eval(np.eye(2)[[1, 0]], np.eye(2)[[0, 0]])
    assert ev.confusion_matrix().sum() == 4
    assert ev.accuracy() == pytest.approx(3 / 4)


def test_roc_auc_perfect_and_random():
    roc = ROC()
    labels = np.array([0, 0, 1, 1])
    roc.eval(labels, np.array([0.1, 0.2, 0.8, 0.9]))
    assert roc.calculate_auc() == pytest.approx(1.0)

    roc2 = ROC()
    roc2.eval(np.array([0, 1, 0, 1]), np.array([0.5, 0.5, 0.5, 0.5]))
    assert roc2.calculate_auc() == pytest.approx(0.5)


def test_roc_known_auc():
    roc = ROC()
    roc.eval(np.array([1, 0, 1, 0]), np.array([0.9, 0.8, 0.7, 0.1]))
    # rank-based AUC: pairs (pos > neg): (0.9>0.8, 0.9>0.1, 0.7>0.1) = 3 of 4
    assert roc.calculate_auc() == pytest.approx(0.75)


def test_regression_eval_known_values():
    ev = RegressionEvaluation()
    y = np.array([[1.0], [2.0], [3.0]])
    p = np.array([[1.5], [2.0], [2.5]])
    ev.eval(y, p)
    assert ev.mean_squared_error() == pytest.approx((0.25 + 0 + 0.25) / 3)
    assert ev.mean_absolute_error() == pytest.approx(1 / 3)
    assert 0 < ev.r_squared() < 1
    assert ev.pearson_correlation() == pytest.approx(1.0)


def test_confusion_grows_for_later_higher_classes():
    ev = Evaluation()
    ev.eval(np.array([0, 1]), np.array([0, 1]))
    ev.eval(np.array([2, 2]), np.array([2, 1]))  # class 2 first seen in batch 2
    assert ev.confusion_matrix().shape == (3, 3)
    assert ev.accuracy() == pytest.approx(3 / 4)


def test_roc_accepts_onehot_labels():
    roc = ROC()
    roc.eval(np.eye(2)[[0, 0, 1, 1]], np.array([0.1, 0.2, 0.8, 0.9]))
    assert roc.calculate_auc() == pytest.approx(1.0)


class TestEvaluationBinary:
    def test_per_output_counts_and_stats(self):
        from deeplearning4j_tpu.eval import EvaluationBinary

        eb = EvaluationBinary()
        labels = np.asarray([[1, 0], [1, 1], [0, 0], [0, 1]], np.float32)
        preds = np.asarray([[0.9, 0.2], [0.4, 0.8], [0.1, 0.7], [0.6, 0.9]],
                           np.float32)
        eb.eval(labels, preds)
        # output 0: tp=1 (row0), fn=1 (row1), tn=1 (row2), fp=1 (row3)
        assert (eb.tp[0], eb.fp[0], eb.tn[0], eb.fn[0]) == (1, 1, 1, 1)
        assert eb.accuracy(0) == 0.5
        # output 1: tp=2 (rows 1,3), fp=1 (row2), tn=1 (row0), fn=0
        assert eb.precision(1) == 2 / 3 and eb.recall(1) == 1.0
        assert "EvaluationBinary (2 outputs)" in eb.stats()

    def test_mask_excludes_entries(self):
        from deeplearning4j_tpu.eval import EvaluationBinary

        eb = EvaluationBinary()
        labels = np.asarray([[1], [0]], np.float32)
        preds = np.asarray([[0.9], [0.9]], np.float32)
        eb.eval(labels, preds, mask=np.asarray([[1], [0]], np.float32))
        assert eb.fp[0] == 0  # the wrong row was masked out
        assert eb.accuracy(0) == 1.0

    def test_shape_and_no_data_guards(self):
        from deeplearning4j_tpu.eval import EvaluationBinary

        with pytest.raises(ValueError, match="no data"):
            EvaluationBinary().accuracy(0)
        eb = EvaluationBinary()
        with pytest.raises(ValueError, match="shape"):
            eb.eval(np.zeros((4, 2)), np.zeros((2, 4)))
        eb.eval(np.zeros((4, 2)), np.zeros((4, 2)))
        with pytest.raises(ValueError, match="outputs"):
            eb.eval(np.zeros((4, 3)), np.zeros((4, 3)))

    def test_all_metrics_no_data_guard_and_1d_shapes(self):
        from deeplearning4j_tpu.eval import EvaluationBinary

        for meth in ("precision", "recall", "f1"):
            with pytest.raises(ValueError, match="no data"):
                getattr(EvaluationBinary(), meth)(0)
        with pytest.raises(ValueError, match="shape"):
            EvaluationBinary().eval(np.zeros(4), np.zeros((2, 2)))


class TestROCBinary:
    def test_per_output_auc_with_mask(self, rng):
        from deeplearning4j_tpu.eval import ROCBinary

        n = 400
        # output 0: strongly separable; output 1: anti-correlated (AUC→0);
        # output 2: random (AUC≈0.5)
        y = rng.integers(0, 2, size=(n, 3)).astype(np.float32)
        s = np.empty((n, 3), np.float32)
        s[:, 0] = y[:, 0] * 0.8 + rng.random(n) * 0.2
        s[:, 1] = (1 - y[:, 1]) * 0.8 + rng.random(n) * 0.2
        s[:, 2] = rng.random(n)
        mask = np.ones((n, 3), np.float32)
        mask[: n // 4, 2] = 0.0  # excluded entries must not change AUC much
        roc = ROCBinary()
        # two accumulation calls (merge semantics)
        roc.eval(y[: n // 2], s[: n // 2], mask=mask[: n // 2])
        roc.eval(y[n // 2:], s[n // 2:], mask=mask[n // 2:])
        assert roc.num_outputs() == 3
        assert roc.calculate_auc(0) > 0.95
        assert roc.calculate_auc(1) < 0.05
        assert 0.35 < roc.calculate_auc(2) < 0.65
        assert "AUC" in roc.stats()

    def test_output_count_mismatch_raises(self, rng):
        from deeplearning4j_tpu.eval import ROCBinary

        roc = ROCBinary()
        roc.eval(np.zeros((4, 2)), np.zeros((4, 2)))
        with pytest.raises(ValueError, match="2 outputs"):
            roc.eval(np.zeros((4, 3)), np.zeros((4, 3)))

    def test_1d_inputs_with_1d_mask(self, rng):
        """Round-5 regression: a 1-D mask must be expanded alongside 1-D
        labels/scores (previously IndexError on mask[:, i])."""
        from deeplearning4j_tpu.eval import ROCBinary

        n = 200
        y = rng.integers(0, 2, size=n).astype(np.float32)
        s = y * 0.8 + rng.random(n).astype(np.float32) * 0.2
        mask = np.ones(n, np.float32)
        mask[: n // 4] = 0.0
        roc = ROCBinary()
        roc.eval(y, s, mask=mask)
        assert roc.num_outputs() == 1
        assert roc.calculate_auc(0) > 0.95
