"""Serving resilience layer (ISSUE 13): rolling weight reload under traffic
(zero shed, zero steady-state recompiles, version surface advancing), canary
rejection keeping the old weights serving, supervised scheduler workers
(crash -> loud 500 + flight-recorder cause -> restart; budget exhausted ->
health flip + fail-fast submits), the per-model circuit-breaker state
machine, SLO-brownout lane ordering, the new serving fault kinds'
``DL4J_TPU_FAULTS`` parsing, and the train->serve publish/watch seam.
Heavy end-to-end cases are ``slow``-marked (the 870s tier-1 budget)."""

import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (BrownoutController, BrownoutShedError,
                                        CircuitBreaker, CircuitOpenError,
                                        ModelLoadError, ModelRouter,
                                        ReloadRejectedError,
                                        SchedulerDrainingError,
                                        SchedulerStoppedError, ServingModel,
                                        WorkerCrashedError)
from deeplearning4j_tpu.serving.scheduler import BatchScheduler
from deeplearning4j_tpu.util import faults as fl
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.faults import get_injector, parse_fault_spec
from deeplearning4j_tpu.util.model_serializer import ModelSerializer

R = np.random.default_rng(11)


@pytest.fixture(autouse=True)
def _clean_injector():
    get_injector().clear()
    yield
    get_injector().clear()


def _dense_net(seed=0, n_in=10, n_out=4):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .batch_buckets((2, 4, 8)).list()
            .layer(DenseLayer(n_in=n_in, n_out=24, activation="relu"))
            .layer(OutputLayer(n_in=24, n_out=n_out, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _archive(tmp_path, name, net):
    path = str(tmp_path / name)
    ModelSerializer.write_model(net, path, save_updater=False)
    return path


def _router_with(model_id="m", seed=0, **reg_kw):
    net = _dense_net(seed)
    router = ModelRouter(name=f"resilience-{model_id}")
    model = ServingModel(net, model_id)
    sched = router.register(model, max_wait_ms=0.5, **reg_kw)
    model.warmup()
    return router, net, model, sched


X2 = R.normal(size=(2, 10)).astype(np.float32)


def _counter(name: str, **labels) -> float:
    return tm.get_telemetry().counter_total(name, **labels)


# --------------------------------------------------------------- fault kinds
class TestServingFaultParsing:
    def test_new_kinds_parse(self):
        faults = parse_fault_spec(
            "serving_compute_error@3,serving_worker_crash,"
            "serving_slow_batch:250,reload_corrupt_archive:0.4")
        by_kind = {f.kind: f for f in faults}
        assert by_kind["serving_compute_error"].at_step == 3
        assert by_kind["serving_worker_crash"].at_step is None
        assert by_kind["serving_slow_batch"].arg == 250.0
        assert by_kind["reload_corrupt_archive"].arg == 0.4

    def test_serving_kinds_are_step_gated(self):
        # @nth = the scheduler's batch-cycle number; legal for the three
        # scheduler-sited kinds, illegal for the reload path (no steps)
        for kind in (fl.SERVING_COMPUTE_ERROR, fl.SERVING_WORKER_CRASH,
                     fl.SERVING_SLOW_BATCH):
            assert parse_fault_spec(f"{kind}@2")[0].at_step == 2
        with pytest.raises(ValueError, match="no step concept"):
            parse_fault_spec("reload_corrupt_archive@2")

    def test_unknown_kind_still_raises(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            parse_fault_spec("serving_typo_error")


# ----------------------------------------------------------- circuit breaker
class TestCircuitBreaker:
    def _clocked(self, **kw):
        t = [0.0]
        br = CircuitBreaker(clock=lambda: t[0], model_id="t", **kw)
        return t, br

    def test_opens_on_consecutive_errors(self):
        _t, br = self._clocked(consecutive_errors=3)
        br.record_error()
        br.record_error()
        assert br.state == "closed"
        br.record_error()
        assert br.state == "open"

    def test_opens_on_error_rate(self):
        _t, br = self._clocked(consecutive_errors=100, error_rate=0.5,
                               window=8, min_samples=8)
        for i in range(8):  # alternating: never 100 consecutive, rate 0.5
            (br.record_error if i % 2 else br.record_success)()
        assert br.state == "open"

    def test_open_fast_fails_with_retry_after(self):
        t, br = self._clocked(consecutive_errors=1, cooldown_s=10.0)
        br.record_error()
        with pytest.raises(CircuitOpenError) as ei:
            br.allow()
        assert ei.value.http_status == 503
        assert 9.0 <= ei.value.retry_after_s <= 10.0

    def test_half_open_probe_bounded_then_closes(self):
        t, br = self._clocked(consecutive_errors=1, cooldown_s=5.0,
                              half_open_probes=1)
        br.record_error()
        t[0] = 6.0
        br.allow()  # the probe
        assert br.state == "half_open"
        with pytest.raises(CircuitOpenError):
            br.allow()  # only one probe may fly
        br.record_success()
        assert br.state == "closed"
        br.allow()  # closed again: free passage

    def test_half_open_failure_reopens(self):
        t, br = self._clocked(consecutive_errors=1, cooldown_s=5.0)
        br.record_error()
        t[0] = 6.0
        br.allow()
        br.record_error()
        assert br.state == "open"
        with pytest.raises(CircuitOpenError):
            br.allow()  # fresh cooldown from the failed probe
        assert br.opens == 2

    def test_success_resets_consecutive_count(self):
        _t, br = self._clocked(consecutive_errors=3, min_samples=100)
        for _ in range(2):
            br.record_error()
        br.record_success()
        for _ in range(2):
            br.record_error()
        assert br.state == "closed"


class TestBreakerOnTraffic:
    def test_compute_errors_open_then_half_open_closes(self):
        router, _net, _model, sched = _router_with("brk")
        try:
            sched.breaker.consecutive_errors = 2
            sched.breaker.cooldown_s = 0.3
            get_injector().inject(fl.SERVING_COMPUTE_ERROR, count=2)
            for _ in range(2):
                with pytest.raises(RuntimeError, match="injected serving"):
                    router.submit("brk", X2).result(timeout=20)
            assert sched.breaker.state == "open"
            # open = fast-fail 503 + Retry-After, never queued
            with pytest.raises(CircuitOpenError):
                router.submit("brk", X2)
            assert sched.counts["shed_circuit_open"] >= 1
            time.sleep(0.4)  # cooldown -> half-open probe allowed through
            out = np.asarray(router.submit("brk", X2).result(timeout=20))
            assert out.shape == (2, 4)
            deadline = time.time() + 5
            while sched.breaker.state != "closed" and time.time() < deadline:
                time.sleep(0.02)
            assert sched.breaker.state == "closed"
        finally:
            router.shutdown()

    def test_breaker_disabled_by_knob(self):
        net = _dense_net()
        model = ServingModel(net, "nobrk")
        sched = BatchScheduler(model, breaker=None)
        assert sched.breaker is None
        sched.shutdown()


# --------------------------------------------------------- supervised worker
class TestWorkerWatchdog:
    def test_crash_fails_batch_loudly_and_restarts(self):
        router, _net, _model, sched = _router_with("wd")
        try:
            restarts0 = _counter("serving.worker_restarts_total", model="wd")
            get_injector().inject(fl.SERVING_WORKER_CRASH, count=1)
            fut = router.submit("wd", X2)
            with pytest.raises(WorkerCrashedError):
                fut.result(timeout=20)
            # the crash is on the flight recorder with its cause
            recs = sched.flight.dump()
            assert any(r["status"] == "error"
                       and str(r["cause"]).startswith("worker_crash")
                       for r in recs)
            assert _counter("serving.worker_restarts_total",
                            model="wd") == restarts0 + 1
            # restarted worker keeps serving
            out = np.asarray(router.submit("wd", X2).result(timeout=20))
            assert out.shape == (2, 4)
            assert sched.stats()["worker_restarts"] == 1
            assert sched.stats()["worker_alive"]
        finally:
            router.shutdown()

    def test_restart_budget_exhaustion_flips_health_and_fails_fast(self):
        router, _net, _model, sched = _router_with("wd2", max_restarts=0)
        try:
            get_injector().inject(fl.SERVING_WORKER_CRASH, count=3)
            fut = router.submit("wd2", X2)
            with pytest.raises(WorkerCrashedError):
                fut.result(timeout=20)
            deadline = time.time() + 5
            while not sched._worker_dead and time.time() < deadline:
                time.sleep(0.02)
            # health check flipped: the model is declared down
            _ok, checks = tm.get_telemetry().health_report()
            check = checks.get("serving.worker.wd2")
            assert check is not None and check["ok"] is False
            # and a LATER submit fails fast instead of hanging forever
            with pytest.raises(SchedulerStoppedError):
                router.submit("wd2", X2)
        finally:
            router.shutdown()
            # the registry is process-global: restore the check so later
            # suites' /healthz assertions see a healthy process
            tm.set_health("serving.worker.wd2", True, "test cleanup")


class TestSubmitFailFast:
    def test_submit_after_shutdown_fails_fast(self):
        """Satellite: submit() to a stopped scheduler raises a clear
        exception instead of enqueueing into a dead queue forever."""
        router, _net, _model, sched = _router_with("stop")
        router.shutdown()
        with pytest.raises(SchedulerStoppedError, match="stopped"):
            sched.submit(X2)

    def test_shutdown_fails_pending_futures_loudly(self):
        """Satellite: futures queued at shutdown resolve with an exception,
        never hang."""
        net = _dense_net()
        model = ServingModel(net, "pend")
        sched = BatchScheduler(model, max_wait_ms=50.0)
        futs = [sched.submit(X2) for _ in range(3)]  # no worker started
        sched.shutdown()
        for f in futs:
            with pytest.raises(SchedulerDrainingError):
                f.result(timeout=5)


# ------------------------------------------------------------ rolling reload
class TestRollingReload:
    def test_reload_swaps_weights_and_advances_version(self, tmp_path):
        router, _net, model, _sched = _router_with("rl", seed=0)
        try:
            new_net = _dense_net(seed=1)
            path = _archive(tmp_path, "v2.zip", new_net)
            before = np.asarray(router.submit("rl", X2).result(timeout=20))
            assert router.reload("rl", path) == 2
            assert model.version == 2
            after = np.asarray(router.submit("rl", X2).result(timeout=20))
            assert not np.array_equal(before, after)
            # served output == the new net's direct forward, bit-identical
            assert np.array_equal(after, np.asarray(new_net.output(X2)))
            assert router.status()["models"]["rl"]["version"] == 2
        finally:
            router.shutdown()

    def test_corrupt_archive_rejected_old_keeps_serving(self, tmp_path):
        """Satellite: a truncated archive raises a clean ModelLoadError and
        the live model is untouched."""
        router, _net, model, _sched = _router_with("rl2")
        try:
            path = _archive(tmp_path, "good.zip", _dense_net(seed=1))
            data = open(path, "rb").read()
            bad = str(tmp_path / "trunc.zip")
            open(bad, "wb").write(data[: len(data) // 2])
            before = np.asarray(router.submit("rl2", X2).result(timeout=20))
            with pytest.raises(ModelLoadError):
                router.reload("rl2", bad)
            assert model.version == 1
            after = np.asarray(router.submit("rl2", X2).result(timeout=20))
            assert np.array_equal(before, after)
            assert _counter("serving.reload_rejected_total", model="rl2",
                            reason="load_error") >= 1
        finally:
            router.shutdown()

    def test_nan_canary_rejected(self, tmp_path):
        import jax

        router, _net, model, _sched = _router_with("rl3")
        try:
            bad_net = _dense_net(seed=2)
            bad_net.params = jax.tree_util.tree_map(
                lambda a: a * np.nan, bad_net.params)
            path = _archive(tmp_path, "nan.zip", bad_net)
            with pytest.raises(ReloadRejectedError, match="canary"):
                router.reload("rl3", path)
            assert model.version == 1
            out = np.asarray(router.submit("rl3", X2).result(timeout=20))
            assert np.all(np.isfinite(out))
        finally:
            router.shutdown()

    def test_structure_mismatch_rejected(self, tmp_path):
        router, _net, model, _sched = _router_with("rl4")
        try:
            other = _dense_net(seed=0, n_in=6)  # different topology
            path = _archive(tmp_path, "other.zip", other)
            with pytest.raises(ReloadRejectedError, match="topology"):
                router.reload("rl4", path)
            assert model.version == 1
        finally:
            router.shutdown()

    def test_reload_corrupt_archive_fault_fires_on_good_archive(
            self, tmp_path):
        """The injected fault corrupts the READ of a good archive — the
        real truncated-zip mechanism — and the reload is rejected while
        the old version keeps serving."""
        router, _net, model, _sched = _router_with("rl5")
        try:
            path = _archive(tmp_path, "good.zip", _dense_net(seed=1))
            get_injector().inject(fl.RELOAD_CORRUPT_ARCHIVE)
            with pytest.raises(ModelLoadError):
                router.reload("rl5", path)
            assert model.version == 1
            # disarmed after one firing: the SAME archive now reloads fine
            assert router.reload("rl5", path) == 2
        finally:
            router.shutdown()

    def test_load_corrupt_archive_never_partially_registers(self, tmp_path):
        """Satellite: router.load() on a truncated archive raises cleanly
        and the registry holds nothing under that id."""
        router = ModelRouter(name="load-clean")
        path = _archive(tmp_path, "good.zip", _dense_net())
        data = open(path, "rb").read()
        bad = str(tmp_path / "trunc.zip")
        open(bad, "wb").write(data[: len(data) // 3])
        with pytest.raises(ModelLoadError):
            router.load("ghost", bad)
        assert "ghost" not in router.model_ids()
        # a good archive under the same id still loads (no tombstone)
        router.load("ghost", path)
        assert "ghost" in router.model_ids()
        router.shutdown()

    @pytest.mark.slow
    def test_reload_storm_under_traffic_zero_shed_zero_recompiles(
            self, tmp_path):
        """The acceptance case: N>=5 rolling reloads under sustained
        traffic complete with 0 shed requests, 0 steady-state recompiles,
        and the version surface advancing."""
        router, _net, model, _sched = _router_with("storm", queue_limit=512)
        try:
            paths = [_archive(tmp_path, f"v{i}.zip", _dense_net(seed=i))
                     for i in range(1, 6)]
            stop = threading.Event()
            outcome = {"ok": 0, "err": []}

            def traffic():
                while not stop.is_set():
                    try:
                        router.submit("storm", X2).result(timeout=60)
                        outcome["ok"] += 1
                    except Exception as e:  # noqa: BLE001 — recorded
                        outcome["err"].append(repr(e))

            threads = [threading.Thread(target=traffic) for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.2)
            rec0 = _counter("serving.recompiles_total", model="storm")
            versions = [router.reload("storm", p) for p in paths]
            time.sleep(0.2)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert versions == [2, 3, 4, 5, 6]
            assert outcome["err"] == []
            assert outcome["ok"] > 0
            assert _counter("serving.recompiles_total",
                            model="storm") - rec0 == 0
        finally:
            router.shutdown()


# ------------------------------------------------------------------ brownout
class TestBrownout:
    def test_lane_ordering_batch_sheds_interactive_serves(self):
        router, _net, _model, sched = _router_with("bo")
        try:
            router.set_brownout(("batch",))
            with pytest.raises(BrownoutShedError) as ei:
                router.submit("bo", X2, lane="batch")
            assert ei.value.http_status == 429
            out = np.asarray(
                router.submit("bo", X2, lane="interactive").result(
                    timeout=20))
            assert out.shape == (2, 4)
            assert sched.counts["shed_brownout"] >= 1
            router.set_brownout(())
            router.submit("bo", X2, lane="batch").result(timeout=20)
        finally:
            router.shutdown()

    def test_interactive_lane_refused_in_shed_set(self):
        router = ModelRouter(name="bo-guard")
        with pytest.raises(ValueError, match="interactive"):
            BrownoutController(router, shed_lanes=("interactive",))

    def test_slo_exhaustion_drives_brownout_and_recovery(self):
        from deeplearning4j_tpu.util import slo

        router, _net, _model, sched = _router_with("bo2")
        ctrl = BrownoutController(router).install()
        try:
            slo.register(slo.SloObjective(
                "bo2-avail", "availability", target=0.999,
                model="synthetic-bo2", windows=(5.0,)))
            tm.counter("serving.completed_total", 1, model="synthetic-bo2",
                       lane="interactive")
            slo.get_engine().evaluate()
            tm.counter("serving.shed_total", 9, model="synthetic-bo2",
                       reason="deadline", lane="interactive")
            slo.get_engine().evaluate()
            assert ctrl.active
            with pytest.raises(BrownoutShedError):
                router.submit("bo2", X2, lane="batch")
            router.submit("bo2", X2, lane="interactive").result(timeout=20)
            # budget recovery (bad traffic ages out of the 5s window)
            deadline = time.time() + 20
            while ctrl.active and time.time() < deadline:
                time.sleep(0.25)
                slo.get_engine().evaluate()
            assert not ctrl.active
            router.submit("bo2", X2, lane="batch").result(timeout=20)
        finally:
            slo.reset()
            router.shutdown()


# ------------------------------------------------------------ slow batch
class TestSlowBatchFault:
    def test_deadline_sheds_behind_a_stalled_batch(self):
        """serving_slow_batch wedges the worker on a real sleep; a request
        whose deadline expires while queued behind it is shed 429, not
        executed late — the contract holds under a wedged worker."""
        router, _net, _model, sched = _router_with("slow")
        try:
            get_injector().inject(fl.SERVING_SLOW_BATCH, arg=400.0)
            slow_fut = router.submit("slow", X2)  # eats the stall
            time.sleep(0.05)  # let the worker open the stalled batch
            doomed = router.submit("slow", X2, deadline_ms=100.0)
            from deeplearning4j_tpu.serving import DeadlineExceededError

            with pytest.raises(DeadlineExceededError):
                doomed.result(timeout=20)
            # the stalled batch itself completes fine (slow, not broken)
            assert np.asarray(slow_fut.result(timeout=20)).shape == (2, 4)
            assert sched.counts["shed_deadline"] >= 1
        finally:
            router.shutdown()


# ------------------------------------------------- review-pass hardening
class TestReviewHardening:
    def test_crash_with_partially_resolved_batch_no_watchdog_death(self):
        """A crash AFTER _run_batch resolved some riders must not re-fail
        FINISHED futures — that raises inside the watchdog's own handler,
        killing it with _worker_dead never set (the exact hang the
        watchdog exists to prevent)."""
        net = _dense_net()
        model = ServingModel(net, "prt")
        sched = BatchScheduler(model, max_wait_ms=50.0)  # no worker
        try:
            f_done = sched.submit(X2)
            f_pend = sched.submit(X2)
            batch = [sched._queues["interactive"].popleft()
                     for _ in range(2)]
            batch[0].future.set_running_or_notify_cancel()
            batch[0].future.set_result("resolved-before-crash")
            with sched._cv:
                sched._current_batch = batch
            assert sched._on_worker_crash(RuntimeError("boom")) is True
            assert f_done.result(timeout=5) == "resolved-before-crash"
            with pytest.raises(WorkerCrashedError):
                f_pend.result(timeout=5)
        finally:
            sched.shutdown()

    def test_half_open_lost_probe_rearms_after_cooldown(self):
        """An admitted probe shed before any batch outcome (queue full,
        deadline) must not wedge the breaker half-open forever: one
        cooldown with no verdict re-arms the probes."""
        t = [0.0]
        br = CircuitBreaker(clock=lambda: t[0], model_id="t",
                            consecutive_errors=1, cooldown_s=5.0,
                            half_open_probes=1)
        br.record_error()
        t[0] = 6.0
        br.allow()  # the probe — then lost, no outcome ever recorded
        with pytest.raises(CircuitOpenError):
            br.allow()
        t[0] = 12.0  # a full cooldown with no verdict
        br.allow()   # fresh probe admitted instead of wedging forever
        br.record_success()
        assert br.state == "closed"

    def test_slo_reset_ends_active_brownout(self):
        """reset() drops exhausted objectives — the brownout hung off
        their breach must see the recovery, not stay shed forever with
        the hook list emptied under it."""
        from deeplearning4j_tpu.util import slo

        router, _net, _model, _sched = _router_with("rst")
        ctrl = BrownoutController(router).install()
        try:
            slo.register(slo.SloObjective(
                "rst-avail", "availability", target=0.999,
                model="synthetic-rst", windows=(5.0,)))
            tm.counter("serving.completed_total", 1, model="synthetic-rst",
                       lane="interactive")
            slo.get_engine().evaluate()
            tm.counter("serving.shed_total", 9, model="synthetic-rst",
                       reason="deadline", lane="interactive")
            slo.get_engine().evaluate()
            assert ctrl.active
            slo.reset()
            assert not ctrl.active
            router.submit("rst", X2, lane="batch").result(timeout=20)
        finally:
            slo.reset()
            router.shutdown()

    def test_uninstall_detaches_from_engine(self):
        from deeplearning4j_tpu.util import slo

        router, _net, _model, _sched = _router_with("uni")
        ctrl = BrownoutController(router).install()
        try:
            ctrl.uninstall()
            slo.register(slo.SloObjective(
                "uni-avail", "availability", target=0.999,
                model="synthetic-uni", windows=(5.0,)))
            tm.counter("serving.completed_total", 1, model="synthetic-uni",
                       lane="interactive")
            slo.get_engine().evaluate()
            tm.counter("serving.shed_total", 9, model="synthetic-uni",
                       reason="deadline", lane="interactive")
            slo.get_engine().evaluate()
            assert not ctrl.active  # detached: the breach no longer acts
            router.submit("uni", X2, lane="batch").result(timeout=20)
        finally:
            slo.reset()
            router.shutdown()

    def test_canary_does_not_consume_stepless_serving_fault(self, tmp_path):
        """A stepless armed serving_compute_error targets the live worker
        (batch cycles); the reload canary runs with _step=None and must
        neither fire it (good weights rejected) nor consume it (the
        worker's recovery never exercised)."""
        router, _net, model, _sched = _router_with("cf")
        try:
            path = _archive(tmp_path, "good.zip", _dense_net(seed=1))
            get_injector().inject(fl.SERVING_COMPUTE_ERROR, count=1)
            assert router.reload("cf", path) == 2  # canary untouched
            # the fault is still armed for its documented target
            with pytest.raises(RuntimeError, match="injected serving"):
                router.submit("cf", X2).result(timeout=20)
        finally:
            router.shutdown()


    def test_restart_budget_pays_back_after_healthy_run(self):
        """max_restarts bounds a crash LOOP, not lifetime crashes: after
        restart_reset_batches clean batches the spent budget resets, so a
        rare transient (one crash a day) never accumulates into a
        permanent 503."""
        router, _net, _model, sched = _router_with(
            "payback", max_restarts=1, restart_reset_batches=2)
        try:
            for round_ in range(3):  # 3 crashes, budget 1 — all survive
                get_injector().inject(fl.SERVING_WORKER_CRASH, count=1)
                with pytest.raises(WorkerCrashedError):
                    router.submit("payback", X2).result(timeout=20)
                assert sched._restarts == 1
                for _ in range(2):  # healthy run pays the budget back
                    router.submit("payback", X2).result(timeout=20)
                deadline = time.time() + 5
                while sched._restarts and time.time() < deadline:
                    time.sleep(0.02)
                assert sched._restarts == 0
            assert sched.stats()["worker_alive"]
        finally:
            router.shutdown()

    def test_breaker_ignores_client_shaped_errors(self):
        """A buggy client's malformed payloads (the server's HTTP 400
        family: ValueError/TypeError/KeyError) fail their own batch but
        must NOT feed the breaker — one bad client must not 503 a healthy
        model for everyone."""
        router, _net, model, sched = _router_with("cli")
        try:
            sched.breaker.consecutive_errors = 1
            real_execute = model.execute

            def bad_execute(payloads, **kw):
                raise ValueError("malformed payload")

            model.execute = bad_execute
            with pytest.raises(ValueError):
                router.submit("cli", X2).result(timeout=20)
            assert sched.breaker.state == "closed"

            def broken_execute(payloads, **kw):
                raise RuntimeError("model fault")

            model.execute = broken_execute  # a REAL model fault still trips
            with pytest.raises(RuntimeError):
                router.submit("cli", X2).result(timeout=20)
            assert sched.breaker.state == "open"
            model.execute = real_execute
        finally:
            router.shutdown()

    def test_injector_fast_path_flag(self):
        """fire() short-circuits without the global lock when nothing was
        ever armed — the serving tier calls it every batch cycle."""
        inj = get_injector()
        assert inj._armed_fast is False  # autouse fixture cleared it
        assert inj.fire(fl.SERVING_COMPUTE_ERROR, step=1) is None
        inj.inject(fl.SERVING_COMPUTE_ERROR)
        assert inj._armed_fast is True
        assert inj.fire(fl.SERVING_COMPUTE_ERROR, step=1) is not None
        inj.clear()
        assert inj._armed_fast is False

    def test_watch_untyped_error_is_loud_and_retried(self, tmp_path):
        """An UNTYPED reload failure (transient fs/warmup error) must not
        consume the publish signature: the poller counts it, records an
        anomaly, and retries the SAME publish on the next poll."""
        router, _net, model, _sched = _router_with("wtr")
        try:
            pub = str(tmp_path / "live.zip")
            ModelSerializer.write_model(_dense_net(seed=1), pub,
                                        save_updater=False)
            real_reload = router.reload
            fails = [1]

            def flaky_reload(model_id, path, **kw):
                if fails[0]:
                    fails[0] -= 1
                    raise RuntimeError("transient warmup failure")
                return real_reload(model_id, path, **kw)

            router.reload = flaky_reload
            errs0 = _counter("serving.watch_errors_total", model="wtr")
            router.watch("wtr", pub, interval_s=0.05)
            # the watcher starts from the CURRENT signature: touch the
            # file (atomic rewrite) so there is a new commit to reload
            ModelSerializer.write_model(_dense_net(seed=2), pub,
                                        save_updater=False)
            deadline = time.time() + 20
            while model.version == 1 and time.time() < deadline:
                time.sleep(0.05)
            assert model.version == 2  # retried past the transient error
            assert _counter("serving.watch_errors_total",
                            model="wtr") == errs0 + 1
        finally:
            router.shutdown()


# ----------------------------------------------------- train->serve seam
class TestPublishWatch:
    def test_commit_hook_fires_on_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.util.checkpoint import ShardedCheckpointer

        net = _dense_net()
        ckpt = ShardedCheckpointer(str(tmp_path / "ck"), log_fn=None)
        seen = []
        ckpt.add_commit_hook(seen.append)
        ckpt.save(0, net, block=True)
        assert seen == [0]

    def test_background_publisher_same_step_latest_wins(self, tmp_path):
        """The training thread hands the publisher a HOST-array snapshot
        (device refs would be freed by the next step's donation — the
        checkpointer's _host_snapshot rule); the writer serializes it
        identically to write_model, back-to-back publishes collapse to
        the newest weights, and stop() ends the writer thread."""
        import threading

        import jax
        from deeplearning4j_tpu.parallel.elastic import _ArchivePublisher
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer as MS

        net_a, net_b = _dense_net(seed=7), _dense_net(seed=8)
        snap_b = MS.snapshot(net_b)
        # host copy, not device refs: every leaf is a materialized ndarray
        assert all(isinstance(leaf, np.ndarray)
                   for leaf in jax.tree_util.tree_leaves(snap_b["params"]))
        path = str(tmp_path / "pub.zip")
        pub = _ArchivePublisher(path, log_fn=None)
        pub.publish(MS.snapshot(net_a), 1)
        pub.publish(snap_b, 2)  # latest wins
        assert pub.flush(timeout=30)
        restored = MS.restore_model(path, load_updater=False)
        for got, want in zip(jax.tree_util.tree_leaves(restored.params),
                             jax.tree_util.tree_leaves(net_b.params)):
            assert np.array_equal(np.asarray(got), np.asarray(want))
        pub.stop(timeout=30)
        assert not any(t.name == "elastic-publish"
                       for t in threading.enumerate())

    def test_atomic_archive_write_leaves_no_tmp(self, tmp_path):
        net = _dense_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path, save_updater=False)
        assert os.path.exists(path)
        assert [n for n in os.listdir(tmp_path) if ".tmp-" in n] == []
        ModelSerializer.restore_model(path, load_updater=False)

    @pytest.mark.slow
    def test_elastic_publish_feeds_watching_router(self, tmp_path):
        """The continuous-deployment loop: ElasticTrainer publishes an
        archive at every checkpoint cadence; a watch()ing router reloads
        it under traffic; the served weights end up the TRAINED ones."""
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
        from deeplearning4j_tpu.parallel.elastic import ElasticTrainer

        rng = np.random.default_rng(0)
        xs = rng.normal(size=(16, 10)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
        it = ArrayDataSetIterator(xs, ys, batch=4)
        pub = str(tmp_path / "live.zip")

        train_net = _dense_net(seed=3)
        router, _net, model, _sched = _router_with("cd", seed=4)
        try:
            router.watch("cd", pub, interval_s=0.1)
            trainer = ElasticTrainer(
                train_net, str(tmp_path / "ck"), checkpoint_every=2,
                membership=None, rollback_on_anomaly=False,
                publish_archive=pub, log_fn=None)
            trainer.fit(it, epochs=2)
            assert _counter("elastic.publishes_total") >= 1
            # wait for the poller to settle on the FINAL publish
            deadline = time.time() + 20
            last = (model.version, time.time())
            while time.time() < deadline:
                v = model.version
                if v > 1 and v == last[0] and time.time() - last[1] > 0.6:
                    break
                if v != last[0]:
                    last = (v, time.time())
                time.sleep(0.05)
            assert model.version > 1
            out = np.asarray(router.submit("cd", xs[:2]).result(timeout=30))
            assert np.array_equal(out, np.asarray(train_net.output(xs[:2])))
            # a rejected publish is remembered, not retry-spun: corrupt the
            # archive in place and assert the version holds
            data = open(pub, "rb").read()
            open(pub, "wb").write(data[: len(data) // 2])
            v_now = model.version
            time.sleep(0.5)
            assert model.version == v_now
            rejected = _counter("serving.reload_rejected_total", model="cd",
                                reason="load_error")
            time.sleep(0.5)
            assert _counter("serving.reload_rejected_total", model="cd",
                            reason="load_error") == rejected  # no spin
        finally:
            router.shutdown()
