"""Capsule family + CNN loss heads + CenterLoss/OCNN + EmbeddingSequence
(VERDICT r2 next-round #4): gradcheck row, JSON round-trip, and a small
capsule-net training run, mirroring the reference gradientcheck suite
(CNNGradientCheckTest / CapsnetGradientCheckTest — path-cite, mount empty).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import gradcheck
from deeplearning4j_tpu.nn.layers_special import (
    CapsuleLayer,
    CapsuleStrengthLayer,
    Cnn3DLossLayer,
    CnnLossLayer,
    CenterLossOutputLayer,
    EmbeddingSequenceLayer,
    OCNNOutputLayer,
    PrimaryCapsules,
)


def _cast_like(p, x):
    leaves = jax.tree_util.tree_leaves(p)
    return x.astype(leaves[0].dtype) if leaves else x


class TestGradients:
    def test_primary_capsules_gradients(self, rng):
        layer = PrimaryCapsules(capsule_dimensions=4, channels=3,
                                kernel_size=(3, 3), stride=(2, 2))
        params, state = layer.initialize(jax.random.PRNGKey(0), (7, 7, 2))
        x = jnp.asarray(rng.standard_normal((2, 7, 7, 2)))

        def loss(p):
            y, _ = layer.apply(p, state, _cast_like(p, x))
            return jnp.sum(y ** 2)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    def test_capsule_layer_gradients(self, rng):
        layer = CapsuleLayer(capsules=3, capsule_dimensions=4, routings=3)
        params, state = layer.initialize(jax.random.PRNGKey(0), (6, 5))
        x = jnp.asarray(rng.standard_normal((2, 6, 5)))

        def loss(p):
            y, _ = layer.apply(p, state, _cast_like(p, x))
            return jnp.sum(y ** 2)

        res = gradcheck.check_model_gradients(loss, params, eps=1e-4)
        assert res.passed, res

    def test_center_loss_gradients(self, rng):
        layer = CenterLossOutputLayer(n_in=5, n_out=3, lambda_coeff=0.1,
                                      alpha=0.5)
        params, state = layer.initialize(jax.random.PRNGKey(0), (5,))
        # move centers off zero so the center gradient is non-trivial
        params["centers"] = jnp.asarray(rng.standard_normal((3, 5)) * 0.3)
        x = jnp.asarray(rng.standard_normal((4, 5)))
        y = jnp.asarray(np.eye(3)[[0, 2, 1, 0]])

        def loss(p):
            return layer.compute_loss(p, state, _cast_like(p, x),
                                      _cast_like(p, y), training=False)

        res = gradcheck.check_model_gradients(loss, params)
        assert res.passed, res

    def test_ocnn_gradients(self, rng):
        layer = OCNNOutputLayer(n_in=5, hidden_size=4, nu=0.1)
        params, state = layer.initialize(jax.random.PRNGKey(0), (5,))
        x = jnp.asarray(rng.standard_normal((6, 5)))

        def loss(p):
            return layer.compute_loss(p, state, _cast_like(p, x), None,
                                      training=False)

        res = gradcheck.check_model_gradients(loss, params)
        assert res.passed, res

    def test_embedding_sequence_gradients(self, rng):
        layer = EmbeddingSequenceLayer(n_in=7, n_out=3, has_bias=True)
        params, state = layer.initialize(jax.random.PRNGKey(1), (4,))
        ids = jnp.asarray(rng.integers(0, 7, size=(2, 4)))

        def loss(p):
            y, _ = layer.apply(p, state, ids)
            return jnp.sum(y.astype(
                jax.tree_util.tree_leaves(p)[0].dtype) ** 2)

        res = gradcheck.check_model_gradients(loss, params)
        assert res.passed, res


class TestLossHeads:
    def test_cnn_loss_layer_matches_manual(self, rng):
        layer = CnnLossLayer(loss="xent", activation="sigmoid")
        logits = jnp.asarray(rng.standard_normal((2, 4, 4, 1)))
        labels = jnp.asarray(
            rng.integers(0, 2, size=(2, 4, 4, 1)).astype(np.float64))
        got = float(layer.compute_loss({}, {}, logits, labels))
        p = jax.nn.sigmoid(logits)
        want = float(jnp.mean(-(labels * jnp.log(p)
                                + (1 - labels) * jnp.log1p(-p))))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_cnn3d_loss_layer_runs(self, rng):
        layer = Cnn3DLossLayer(loss="mse", activation="identity")
        x = jnp.asarray(rng.standard_normal((2, 3, 4, 4, 2)))
        y = jnp.asarray(rng.standard_normal((2, 3, 4, 4, 2)))
        v = float(layer.compute_loss({}, {}, x, y))
        np.testing.assert_allclose(v, float(jnp.mean((x - y) ** 2)), rtol=1e-4)

    def test_ocnn_r_converges_to_quantile(self, rng):
        """Gradient descent on r solves the nu-quantile stationarity —
        the reference's explicit quantile re-solve, recovered by SGD."""
        from deeplearning4j_tpu.nn.updaters import Sgd

        layer = OCNNOutputLayer(n_in=4, hidden_size=6, nu=0.2)
        params, state = layer.initialize(jax.random.PRNGKey(0), (4,))
        x = jnp.asarray(rng.standard_normal((256, 4)).astype(np.float32))
        upd = Sgd(0.05)
        opt = upd.init_state({"r": params["r"]})

        @jax.jit
        def step(r, opt, i):
            def only_r(rv):
                p = dict(params)
                p["r"] = rv
                return layer.compute_loss(p, state, x, None)

            g = jax.grad(only_r)(r)
            from deeplearning4j_tpu.nn import updaters as U
            new, opt2 = U.apply_updater(upd, {"r": r}, {"r": g}, opt, i)
            return new["r"], opt2

        r = params["r"]
        for i in range(400):
            r, opt = step(r, opt, jnp.asarray(i))
        scores = np.asarray(layer._score(params, x))
        want = np.quantile(scores, layer.nu)
        assert abs(float(r) - want) < 0.05, (float(r), want)


class TestSerialization:
    @pytest.mark.parametrize("layer", [
        CnnLossLayer(loss="xent", activation="sigmoid"),
        Cnn3DLossLayer(),
        CenterLossOutputLayer(n_in=5, n_out=3, alpha=0.1, lambda_coeff=1e-3),
        OCNNOutputLayer(n_in=5, hidden_size=7, nu=0.1),
        EmbeddingSequenceLayer(n_in=11, n_out=6, has_bias=True),
        PrimaryCapsules(capsule_dimensions=8, channels=4, kernel_size=(5, 5)),
        CapsuleLayer(capsules=10, capsule_dimensions=16, routings=2),
        CapsuleStrengthLayer(),
    ])
    def test_json_roundtrip(self, layer):
        from deeplearning4j_tpu.nn.layers import layer_from_dict

        back = layer_from_dict(layer.to_dict())
        assert back == layer


class TestCapsNetTraining:
    def test_capsnet_trains_small_mnist_like(self, rng):
        """PrimaryCapsules -> CapsuleLayer -> CapsuleStrengthLayer trains on
        a small synthetic digit task (the reference's capsnet MNIST config,
        shrunk to CI size)."""
        from deeplearning4j_tpu.nn import (
            InputType,
            MultiLayerNetwork,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import ConvolutionLayer, LossLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(2e-2))
                .list()
                .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                        padding="VALID", activation="relu"))
                .layer(PrimaryCapsules(capsule_dimensions=4, channels=4,
                                       kernel_size=(3, 3), stride=(2, 2)))
                .layer(CapsuleLayer(capsules=3, capsule_dimensions=6,
                                    routings=3))
                # capsule lengths live in [0,1): mse-to-one-hot is the
                # margin-style objective that can actually reach 0 (softmax
                # cross-entropy on lengths floors at -log softmax(1,0,0))
                .layer(CapsuleStrengthLayer())
                .layer(LossLayer(loss="mse", activation="identity"))
                .set_input_type(InputType.convolutional(10, 10, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        # 3 synthetic "digit" prototypes + noise
        protos = rng.standard_normal((3, 10, 10, 1)).astype(np.float32)
        ys = rng.integers(0, 3, 96)
        xs = (protos[ys] + 0.3 * rng.standard_normal((96, 10, 10, 1))
              ).astype(np.float32)
        yoh = np.eye(3, dtype=np.float32)[ys]
        s0 = net.score(x=xs, y=yoh)
        net.fit(xs, yoh, epochs=120)
        assert net.score(x=xs, y=yoh) < s0 * 0.5
        acc = (np.argmax(np.asarray(net.output(xs)), 1) == ys).mean()
        assert acc > 0.9, acc
