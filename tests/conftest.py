"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths are
exercised without TPU hardware — the same trick the reference uses for
"distributed without a cluster" (embedded Aeron MediaDriver + local[N] Spark;
SURVEY.md §4). Must set env vars before jax is imported anywhere.
"""

import os

# Force CPU: the driver environment presets JAX_PLATFORMS=axon (the one real
# TPU chip); tests need determinism, fp32 precision, and 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# x64 stays globally off (TPU-realistic dtypes); gradient checks get double
# precision locally via the jax.enable_x64() context manager in gradcheck.py.

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The env var alone does not win over the preset axon platform in this image;
# the config update does (must run before any device/computation is touched).
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Bound the number of live compiled executables: a full-suite process
    accumulates ~1000 XLA:CPU executables, after which the compiler was
    observed to segfault on a trivial program (flaky, end-of-suite, not
    host OOM — 123 GB free at the time). Clearing per module keeps the
    working set small; per-module recompiles are already the norm since
    shapes differ between files."""
    yield
    jax.clear_caches()
