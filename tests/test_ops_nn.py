"""NN-op tests: conv/pool/norm/softmax/loss/attention vs independent references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import ops


def _np_conv2d_valid(x, w, strides=(1, 1)):
    """Naive NHWC/HWIO conv, VALID padding — independent reference."""
    n, h, wdt, cin = x.shape
    kh, kw, _, cout = w.shape
    sh, sw = strides
    oh = (h - kh) // sh + 1
    ow = (wdt - kw) // sw + 1
    out = np.zeros((n, oh, ow, cout), dtype=np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i * sh : i * sh + kh, j * sw : j * sw + kw, :]
            out[:, i, j, :] = np.tensordot(patch, w, axes=([1, 2, 3], [0, 1, 2]))
    return out


def test_conv2d_valid_matches_naive(rng):
    x = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 3, 5)).astype(np.float32)
    out = ops.exec_op("conv2d", jnp.asarray(x), jnp.asarray(w), padding="VALID")
    np.testing.assert_allclose(out, _np_conv2d_valid(x, w), rtol=1e-4, atol=1e-5)


def test_conv2d_stride_and_bias(rng):
    x = rng.standard_normal((1, 9, 9, 2)).astype(np.float32)
    w = rng.standard_normal((3, 3, 2, 4)).astype(np.float32)
    b = rng.standard_normal((4,)).astype(np.float32)
    out = ops.exec_op("conv2d", jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                      strides=(2, 2), padding="VALID")
    np.testing.assert_allclose(
        out, _np_conv2d_valid(x, w, (2, 2)) + b, rtol=1e-4, atol=1e-5
    )


def test_conv2d_same_shape():
    x = jnp.zeros((2, 14, 14, 8))
    w = jnp.zeros((3, 3, 8, 16))
    out = ops.exec_op("conv2d", x, w, padding="SAME")
    assert out.shape == (2, 14, 14, 16)


def test_depthwise_conv_shape():
    x = jnp.zeros((2, 8, 8, 6))
    w = jnp.zeros((3, 3, 6, 2))
    out = ops.exec_op("depthwise_conv2d", x, w, padding="SAME")
    assert out.shape == (2, 8, 8, 12)


def test_conv1d(rng):
    x = rng.standard_normal((2, 10, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 4)).astype(np.float32)
    out = ops.exec_op("conv1d", jnp.asarray(x), jnp.asarray(w), padding="VALID")
    assert out.shape == (2, 8, 4)
    # spot check one element via naive conv
    expect0 = np.tensordot(x[0, 0:3, :], w, axes=([0, 1], [0, 1]))
    np.testing.assert_allclose(out[0, 0], expect0, rtol=1e-4)


def test_maxpool_avgpool(rng):
    x = rng.standard_normal((1, 4, 4, 2)).astype(np.float32)
    mx = ops.exec_op("maxpool2d", jnp.asarray(x), kernel=(2, 2))
    av = ops.exec_op("avgpool2d", jnp.asarray(x), kernel=(2, 2))
    assert mx.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(mx[0, 0, 0], x[0, :2, :2].max(axis=(0, 1)))
    np.testing.assert_allclose(av[0, 0, 0], x[0, :2, :2].mean(axis=(0, 1)), rtol=1e-6)


def test_global_pooling(rng):
    x = rng.standard_normal((2, 5, 5, 3)).astype(np.float32)
    np.testing.assert_allclose(
        ops.exec_op("global_avg_pool", jnp.asarray(x)), x.mean(axis=(1, 2)), rtol=1e-5
    )


def test_batchnorm_inference(rng):
    x = rng.standard_normal((4, 6)).astype(np.float32)
    mean = x.mean(axis=0)
    var = x.var(axis=0)
    gamma = rng.standard_normal((6,)).astype(np.float32)
    beta = rng.standard_normal((6,)).astype(np.float32)
    out = ops.exec_op("batchnorm", jnp.asarray(x), jnp.asarray(mean), jnp.asarray(var),
                      jnp.asarray(gamma), jnp.asarray(beta), eps=1e-5)
    expect = (x - mean) / np.sqrt(var + 1e-5) * gamma + beta
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_batchnorm_train_updates_running_stats(rng):
    x = rng.standard_normal((16, 4)).astype(np.float32) * 3 + 1
    gamma = np.ones(4, np.float32)
    beta = np.zeros(4, np.float32)
    out, new_mean, new_var = ops.exec_op(
        "batchnorm_train", jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta),
        jnp.zeros(4), jnp.ones(4), momentum=0.0,
    )
    np.testing.assert_allclose(np.asarray(out).mean(axis=0), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(new_mean, x.mean(axis=0), rtol=1e-4)
    np.testing.assert_allclose(new_var, x.var(axis=0, ddof=1), rtol=1e-3)


def test_layernorm(rng):
    x = rng.standard_normal((3, 8)).astype(np.float32)
    out = np.asarray(ops.exec_op("layernorm", jnp.asarray(x)))
    np.testing.assert_allclose(out.mean(axis=-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(axis=-1), 1.0, atol=1e-3)


def test_softmax_and_logsoftmax(rng):
    x = rng.standard_normal((5, 9)).astype(np.float32)
    s = np.asarray(ops.exec_op("softmax", jnp.asarray(x)))
    np.testing.assert_allclose(s.sum(axis=-1), 1.0, rtol=1e-5)
    ref = np.exp(x) / np.exp(x).sum(axis=-1, keepdims=True)
    np.testing.assert_allclose(s, ref, rtol=1e-4)
    np.testing.assert_allclose(
        ops.exec_op("log_softmax", jnp.asarray(x)), np.log(ref), rtol=1e-4, atol=1e-5
    )


def test_softmax_cross_entropy_matches_manual(rng):
    logits = rng.standard_normal((4, 6)).astype(np.float32)
    labels = np.eye(6, dtype=np.float32)[[0, 3, 2, 5]]
    loss = ops.exec_op("softmax_cross_entropy", jnp.asarray(logits), jnp.asarray(labels))
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    expect = -(labels * logp).sum(-1).mean()
    np.testing.assert_allclose(loss, expect, rtol=1e-5)


def test_sparse_vs_dense_xent(rng):
    logits = rng.standard_normal((4, 6)).astype(np.float32)
    idx = np.array([1, 0, 5, 2])
    dense = ops.exec_op("softmax_cross_entropy", jnp.asarray(logits),
                        jnp.asarray(np.eye(6, dtype=np.float32)[idx]))
    sparse = ops.exec_op("sparse_softmax_cross_entropy", jnp.asarray(logits), jnp.asarray(idx))
    np.testing.assert_allclose(dense, sparse, rtol=1e-6)


def test_sigmoid_xent_stable_large_logits():
    logits = jnp.array([[100.0, -100.0]])
    labels = jnp.array([[1.0, 0.0]])
    loss = ops.exec_op("sigmoid_cross_entropy", logits, labels)
    assert np.isfinite(float(loss)) and float(loss) < 1e-4


def test_mse_huber(rng):
    p = rng.standard_normal((8, 3)).astype(np.float32)
    t = rng.standard_normal((8, 3)).astype(np.float32)
    np.testing.assert_allclose(
        ops.exec_op("mse_loss", jnp.asarray(p), jnp.asarray(t)),
        np.mean((p - t) ** 2), rtol=1e-5,
    )
    h = float(ops.exec_op("huber_loss", jnp.asarray(p), jnp.asarray(t), delta=1e9))
    np.testing.assert_allclose(h, 0.5 * np.mean((p - t) ** 2), rtol=1e-4)


def test_attention_uniform_when_keys_identical(rng):
    # identical keys → softmax uniform → output = mean of values
    q = jnp.asarray(rng.standard_normal((1, 1, 4, 8)).astype(np.float32))
    k = jnp.ones((1, 1, 6, 8), dtype=jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 1, 6, 8)).astype(np.float32))
    out = ops.exec_op("dot_product_attention", q, k, v)
    np.testing.assert_allclose(
        out[0, 0, 0], np.asarray(v)[0, 0].mean(axis=0), rtol=1e-4, atol=1e-5
    )


def test_attention_causal_mask(rng):
    q = jnp.asarray(rng.standard_normal((1, 1, 4, 8)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((1, 1, 4, 8)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((1, 1, 4, 8)).astype(np.float32))
    causal = ops.exec_op("dot_product_attention", q, k, v, is_causal=True)
    # position 0 attends only to key 0 → equals v[0]
    np.testing.assert_allclose(causal[0, 0, 0], v[0, 0, 0], rtol=1e-4, atol=1e-5)


def test_mha_shapes(rng):
    b, t, d, h = 2, 5, 16, 4
    x = jnp.asarray(rng.standard_normal((b, t, d)).astype(np.float32))
    wq = jnp.asarray(rng.standard_normal((d, d)).astype(np.float32)) * 0.1
    wo = jnp.asarray(rng.standard_normal((d, d)).astype(np.float32)) * 0.1
    out = ops.exec_op("multihead_attention", x, x, wq, wq, wq, wo, h)
    assert out.shape == (b, t, d)
    # the ND4J-parity name routes to the three-input q/k/v op (the two used
    # to collide in the registry — review finding, round 3)
    out2 = ops.exec_op("multiHeadDotProductAttention",
                       x, x, x, wq, wq, wq, wo, n_heads=h)
    assert out2.shape == (b, t, d)


def test_conv_grad_flows(rng):
    x = jnp.asarray(rng.standard_normal((1, 6, 6, 2)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 3, 2, 3)).astype(np.float32))

    def loss(w):
        return jnp.sum(ops.exec_op("conv2d", x, w, padding="VALID") ** 2)

    g = jax.grad(loss)(w)
    assert g.shape == w.shape
    assert float(jnp.abs(g).sum()) > 0


def test_pool_explicit_padding_nchw(rng):
    # regression: explicit (ph, pw) padding must land on H/W for NCHW too
    x = rng.standard_normal((1, 2, 4, 4)).astype(np.float32)
    out = ops.exec_op("maxpool2d", jnp.asarray(x), kernel=(3, 3), strides=(1, 1),
                      padding=(1, 1), data_format="NCHW")
    assert out.shape == (1, 2, 4, 4)
    np.testing.assert_allclose(out[0, 0, 1, 1], x[0, 0, :3, :3].max(), rtol=1e-6)


def test_avgpool3d_same_count_normalized():
    x = jnp.ones((1, 3, 3, 3, 1))
    out = ops.exec_op("avgpool3d", x, kernel=(2, 2, 2), strides=(1, 1, 1), padding="SAME")
    # all-ones input: correct count normalization gives exactly 1 everywhere
    np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-6)


def test_conv3d_bias_ncdhw(rng):
    x = rng.standard_normal((1, 2, 4, 4, 4)).astype(np.float32)  # NCDHW, C=2
    w = rng.standard_normal((1, 1, 1, 2, 2)).astype(np.float32)
    b = np.array([10.0, 20.0], dtype=np.float32)
    out = ops.exec_op("conv3d", jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                      data_format="NCDHW")
    out0 = ops.exec_op("conv3d", jnp.asarray(x), jnp.asarray(w), None,
                       data_format="NCDHW")
    np.testing.assert_allclose(np.asarray(out) - np.asarray(out0),
                               np.array([10.0, 20.0]).reshape(1, 2, 1, 1, 1)
                               * np.ones_like(out0), rtol=1e-5)


def test_nll_loss_all_targets_ignored_returns_zero():
    """ADVICE r5: mean reduction with every target == ignore_index used to
    divide by the 1e-12 clamp and return picked.sum() * 1e12 garbage; an
    all-ignored batch contributes exactly 0 loss (and 0 gradient)."""
    lp = jnp.asarray(np.log(np.full((3, 4), 0.25, np.float32)))
    target = jnp.asarray([9, 9, 9])
    out = ops.exec_op("nll_loss", lp, target, ignore_index=9)
    np.testing.assert_allclose(np.asarray(out), 0.0)
    # gradient stays finite/zero rather than 1e12-scaled
    g = jax.grad(lambda l: ops.exec_op("nll_loss", l, target,
                                       ignore_index=9))(lp)
    np.testing.assert_allclose(np.asarray(g), 0.0)
    # mixed batch still weight-normalizes over the non-ignored elements
    mixed = jnp.asarray([0, 9, 2])
    out = ops.exec_op("nll_loss", lp, mixed, ignore_index=9)
    np.testing.assert_allclose(np.asarray(out), np.log(4.0), rtol=1e-6)
