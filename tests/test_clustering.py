"""Clustering + nearest-neighbour + t-SNE tests.

Reference test parity: deeplearning4j-nearestneighbors-parent tests
(KMeansTest, VPTreeTest, KDTreeTest) and BarnesHutTsne's convergence tests —
each structure is validated against brute force / known geometry.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (KDTree, KMeans,
                                           RandomProjectionLSH, VPTree)
from deeplearning4j_tpu.manifold import Tsne

R = np.random.default_rng(3)


def _blobs(n_per=20, d=5, centers=((0,) * 5, (8,) * 5, (-8, 8, -8, 8, -8))):
    xs, labels = [], []
    for li, c in enumerate(centers):
        xs.append(R.normal(size=(n_per, d)).astype(np.float32)
                  + np.asarray(c, np.float32))
        labels += [li] * n_per
    return np.concatenate(xs), np.asarray(labels)


class TestKMeans:
    def test_recovers_blobs(self):
        x, labels = _blobs()
        km = KMeans(k=3, seed=1).fit(x)
        # each true cluster must map to exactly one predicted cluster
        mapping = {}
        for li in range(3):
            pred = km.labels[labels == li]
            assert len(set(pred.tolist())) == 1, "cluster split"
            mapping[li] = pred[0]
        assert len(set(mapping.values())) == 3, "clusters merged"
        # centers near the true means
        for li, c in enumerate(km.centers[list(mapping.values())]):
            true_mean = x[labels == li].mean(axis=0)
            assert np.linalg.norm(c - true_mean) < 1.0

    def test_predict_matches_fit_labels(self):
        x, _ = _blobs()
        km = KMeans(k=3, seed=1).fit(x)
        np.testing.assert_array_equal(km.predict(x), km.labels)

    def test_inertia_decreases_with_k(self):
        x, _ = _blobs()
        i2 = KMeans(k=2, seed=1).fit(x).inertia
        i6 = KMeans(k=6, seed=1).fit(x).inertia
        assert i6 < i2

    def test_random_init_and_convergence_iterations(self):
        x, _ = _blobs()
        km = KMeans(k=3, init="random", seed=4).fit(x)
        assert km.n_iterations <= km.max_iterations
        assert km.inertia is not None and np.isfinite(km.inertia)


def _brute_knn(items, x, k, metric="euclidean"):
    if metric == "euclidean":
        d = np.linalg.norm(items - x, axis=1)
    else:
        na = np.linalg.norm(items, axis=1) * np.linalg.norm(x)
        d = 1 - (items @ x) / np.maximum(na, 1e-12)
    order = np.argsort(d, kind="stable")[:k]
    return order.tolist(), d[order].tolist()


class TestTrees:
    def test_vptree_exact_vs_bruteforce(self):
        items = R.normal(size=(200, 8))
        tree = VPTree(items)
        for _ in range(10):
            q = R.normal(size=8)
            idx, dist = tree.query(q, k=5)
            bidx, bdist = _brute_knn(items, q, 5)
            np.testing.assert_allclose(sorted(dist), sorted(bdist),
                                       rtol=1e-10)
            assert set(idx) == set(bidx)

    def test_vptree_cosine(self):
        items = R.normal(size=(100, 6))
        tree = VPTree(items, distance="cosine")
        q = R.normal(size=6)
        idx, dist = tree.query(q, k=3)
        bidx, bdist = _brute_knn(items, q, 3, metric="cosine")
        np.testing.assert_allclose(sorted(dist), sorted(bdist), rtol=1e-10)
        assert set(idx) == set(bidx)

    def test_kdtree_exact_vs_bruteforce(self):
        items = R.normal(size=(300, 3))
        tree = KDTree(items)
        for _ in range(10):
            q = R.normal(size=3)
            idx, dist = tree.query(q, k=4)
            bidx, bdist = _brute_knn(items, q, 4)
            np.testing.assert_allclose(sorted(dist), sorted(bdist),
                                       rtol=1e-10)
            assert set(idx) == set(bidx)

    def test_vptree_duplicate_heavy_data(self):
        """Review-finding regression: all-tied distances must not recurse
        once per point (RecursionError at N=2000 before the positional
        split fallback)."""
        items = np.zeros((2000, 3))
        items[:5] += np.arange(5)[:, None]  # a few distinct rows
        tree = VPTree(items)
        idx, dist = tree.query(np.asarray([4.0, 4.0, 4.0]), k=1)
        assert dist[0] == 0.0 and np.allclose(items[idx[0]], 4.0)

    def test_k1_is_nearest(self):
        items = np.asarray([[0.0, 0.0], [5.0, 5.0], [1.0, 1.0]])
        for tree in (VPTree(items), KDTree(items)):
            idx, dist = tree.query(np.asarray([0.9, 0.9]), k=1)
            assert idx == [2]


class TestLSH:
    def test_exact_bucket_hit(self):
        items = R.normal(size=(150, 16)).astype(np.float32)
        lsh = RandomProjectionLSH(hash_bits=12, seed=2).fit(items)
        # querying a stored item must return it first (distance 0)
        idx, dist = lsh.query(items[17], k=1)
        assert idx[0] == 17
        assert dist[0] < 1e-6

    def test_approximate_recall(self):
        items = R.normal(size=(300, 10)).astype(np.float32)
        lsh = RandomProjectionLSH(hash_bits=10, seed=2).fit(items)
        hits = 0
        for _ in range(20):
            q = R.normal(size=10).astype(np.float32)
            idx, _ = lsh.query(q, k=5, max_probes=64, oversample=8)
            bidx, _ = _brute_knn(items, q, 5, metric="cosine")
            hits += len(set(idx) & set(bidx))
        assert hits / (20 * 5) > 0.5, "LSH recall collapsed"

    def test_max_probes_is_a_cap(self):
        """Review-finding regression: a query whose first bucket already
        holds oversample*k candidates must stop after ONE probe."""
        items = np.ones((50, 8), np.float32) + R.normal(
            size=(50, 8)).astype(np.float32) * 1e-3  # one dense bucket
        lsh = RandomProjectionLSH(hash_bits=8, seed=0).fit(items)
        probed = {"n": 0}
        orig = dict(lsh._buckets)

        class Counting(dict):
            def __getitem__(self, key):
                probed["n"] += 1
                return orig[key]

        lsh._buckets = Counting(orig)
        lsh.query(items[0], k=2, max_probes=64)
        assert probed["n"] == 1


class TestTsne:
    def test_blobs_separate(self):
        x, labels = _blobs(n_per=15, d=8,
                           centers=((0,) * 8, (10,) * 8,
                                    (-10, 10) * 4))
        emb = Tsne(perplexity=10, n_iter=300, seed=0).fit_transform(x)
        assert emb.shape == (45, 2)
        intra, inter = [], []
        for i in range(3):
            pts = emb[labels == i]
            intra.append(np.mean(np.linalg.norm(
                pts - pts.mean(axis=0), axis=1)))
            for j in range(i + 1, 3):
                inter.append(np.linalg.norm(
                    pts.mean(axis=0) - emb[labels == j].mean(axis=0)))
        assert min(inter) > 2.0 * max(intra), (intra, inter)

    def test_affinity_perplexity_calibration(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.manifold.tsne import (
            _calibrate_affinities, _pairwise_sq_dists)

        x = jnp.asarray(R.normal(size=(60, 4)).astype(np.float32))
        target = 12.0
        p = np.asarray(_calibrate_affinities(_pairwise_sq_dists(x), target))
        # effective perplexity = 2^H(row) must hit the target
        h = -np.sum(np.where(p > 0, p * np.log2(np.maximum(p, 1e-20)), 0),
                    axis=1)
        np.testing.assert_allclose(2.0 ** h, target, rtol=0.05)

    def test_kl_is_finite_and_small_vs_random(self):
        rng = np.random.default_rng(11)
        x = np.concatenate([
            rng.normal(size=(12, 6)).astype(np.float32) + np.asarray(c,
                                                                     np.float32)
            for c in ((0,) * 6, (9,) * 6, (-9, 9) * 3)])
        t = Tsne(perplexity=8, n_iter=250, seed=0).fit(x)
        assert np.isfinite(t.kl_divergence)
        # optimized KL must beat the KL of the random init by a wide margin
        t0 = Tsne(perplexity=8, n_iter=1, seed=0).fit(x)
        assert t.kl_divergence < t0.kl_divergence * 0.5

    def test_perplexity_guard(self):
        with pytest.raises(ValueError):
            Tsne(perplexity=30).fit(np.zeros((10, 3), np.float32))
