"""TF GraphDef + ONNX import → SameDiff, with golden outputs.

Reference test parity: the TF-import regression suite (SURVEY.md §4:
"frozen TF graphs + saved input/output pairs, TFGraphTestAllSameDiff-style")
— here the frozen graphs are generated in-test with the installed tensorflow
and the goldens come from executing them with TF itself; ONNX bytes are
authored with the protomini codec (no onnx package in the image) and checked
against numpy/torch math.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.imports import import_graph_def, import_onnx  # noqa: E402
from deeplearning4j_tpu.imports import protomini as pm  # noqa: E402


def _freeze(fn, feeds):
    """Build a tf.function graph, return (graph_def, golden_outputs, out_names)."""
    conc = tf.function(fn).get_concrete_function(
        *[tf.TensorSpec(v.shape, v.dtype) for v in feeds])
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    golden = [np.asarray(t) for t in frozen(*[tf.constant(v) for v in feeds])]
    in_names = [i.name.split(":")[0] for i in frozen.inputs]
    out_names = [o.name for o in frozen.outputs]
    return gd, golden, in_names, out_names


def _golden_match(gd, golden, in_names, out_names, feeds, atol=1e-5):
    sd = import_graph_def(gd)
    keys = [sd.tf_name_map[o if ":" in o else o + ":0"] for o in out_names]
    res = sd.output({n: v for n, v in zip(in_names, feeds)}, keys)
    for key, g in zip(keys, golden):
        np.testing.assert_allclose(np.asarray(res[key]), g, atol=atol, rtol=1e-4)


class TestTFImport:
    def test_mlp(self, rng):
        w1 = tf.constant(rng.normal(size=(4, 8)).astype(np.float32) * 0.3)
        b1 = tf.constant(np.zeros(8, np.float32))
        w2 = tf.constant(rng.normal(size=(8, 3)).astype(np.float32) * 0.3)

        def mlp(x):
            h = tf.nn.relu(tf.matmul(x, w1) + b1)
            return tf.nn.softmax(tf.matmul(h, w2))

        x = rng.normal(size=(5, 4)).astype(np.float32)
        _golden_match(*_freeze(mlp, [x]), [x])

    def test_layernorm_gelu_block(self, rng):
        g = tf.constant(np.ones(6, np.float32) * 1.3)
        b = tf.constant(np.zeros(6, np.float32) + 0.1)

        def block(x):
            mu = tf.reduce_mean(x, axis=-1, keepdims=True)
            var = tf.reduce_mean(tf.square(x - mu), axis=-1, keepdims=True)
            h = (x - mu) * tf.math.rsqrt(var + 1e-6) * g + b
            # tanh-free exact gelu via erf (BERT's formulation)
            return h * 0.5 * (1.0 + tf.math.erf(h / np.sqrt(2.0).astype(np.float32)))

        x = rng.normal(size=(2, 7, 6)).astype(np.float32)
        _golden_match(*_freeze(block, [x]), [x])

    def test_attention_block(self, rng):
        wq = tf.constant(rng.normal(size=(8, 8)).astype(np.float32) * 0.3)
        wk = tf.constant(rng.normal(size=(8, 8)).astype(np.float32) * 0.3)
        wv = tf.constant(rng.normal(size=(8, 8)).astype(np.float32) * 0.3)

        def attn(x):  # (B,T,8), 2 heads
            q = tf.reshape(tf.matmul(x, wq), (2, 5, 2, 4))
            k = tf.reshape(tf.matmul(x, wk), (2, 5, 2, 4))
            v = tf.reshape(tf.matmul(x, wv), (2, 5, 2, 4))
            q = tf.transpose(q, (0, 2, 1, 3))
            k = tf.transpose(k, (0, 2, 1, 3))
            v = tf.transpose(v, (0, 2, 1, 3))
            s = tf.matmul(q, k, adjoint_b=True) / 2.0
            w = tf.nn.softmax(s)
            o = tf.transpose(tf.matmul(w, v), (0, 2, 1, 3))
            return tf.reshape(o, (2, 5, 8))

        x = rng.normal(size=(2, 5, 8)).astype(np.float32)
        _golden_match(*_freeze(attn, [x]), [x])

    def test_cnn(self, rng):
        w = tf.constant(rng.normal(size=(3, 3, 2, 4)).astype(np.float32) * 0.3)

        def cnn(x):
            h = tf.nn.conv2d(x, w, strides=[1, 1, 1, 1], padding="SAME")
            h = tf.nn.relu(h)
            h = tf.nn.max_pool2d(h, ksize=2, strides=2, padding="VALID")
            return tf.reduce_mean(h, axis=[1, 2])

        x = rng.normal(size=(2, 8, 8, 2)).astype(np.float32)
        _golden_match(*_freeze(cnn, [x]), [x])

    def test_embedding_gather_concat(self, rng):
        table = tf.constant(rng.normal(size=(10, 4)).astype(np.float32))

        def emb(ids):
            e = tf.gather(table, ids)
            parts = tf.split(e, 2, axis=1)
            return tf.concat([parts[1], parts[0]], axis=1)

        ids = rng.integers(0, 10, size=(3, 4)).astype(np.int32)
        _golden_match(*_freeze(emb, [ids]), [ids])

    def test_strided_slice_pad_tile(self, rng):
        def fn(x):
            h = x[:, 1:4]
            h = tf.pad(h, [[0, 0], [1, 1]])
            return tf.tile(h, [1, 2])

        x = rng.normal(size=(2, 6)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_space_to_batch_nd(self, rng):
        def fn(x):
            y = tf.raw_ops.SpaceToBatchND(input=x, block_shape=[2, 2],
                                          paddings=[[1, 0], [0, 1]])
            return tf.raw_ops.BatchToSpaceND(input=y, block_shape=[2, 2],
                                             crops=[[1, 0], [0, 1]])

        x = rng.normal(size=(1, 5, 5, 2)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_strided_slice_ellipsis_newaxis(self, rng):
        """StridedSlice ellipsis/new_axis masks (VERDICT r2 missing #4):
        pure index arithmetic onto getitem's ("e",)/("n",) spec entries."""
        def fn(x):
            a = x[..., 1]            # ellipsis + shrink
            b = x[:, tf.newaxis]     # new_axis
            c = x[0, ..., ::2]       # shrink + ellipsis + stride
            d = x[..., tf.newaxis, :]  # ellipsis + new_axis
            return a, b, c, d

        x = rng.normal(size=(2, 3, 4)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_unsupported_op_reports_name(self):
        # round 5 implemented the previous example (Betainc); use a
        # permanently-waived family instead (string ops, WAIVED.md)
        def fn(x):
            return tf.strings.length(tf.strings.as_string(x))

        x = np.abs(np.random.default_rng(0).normal(size=(3,))).astype(np.float32)
        gd, *_ = _freeze(fn, [x])
        with pytest.raises(NotImplementedError, match="AsString|StringLength"):
            import_graph_def(gd)


# ---------------------------------------------------------------------------
# ONNX
# ---------------------------------------------------------------------------


def _onnx_tensor(name, arr):
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): 1, np.dtype(np.int64): 7,
          np.dtype(np.int32): 6, np.dtype(np.uint8): 2,
          np.dtype(np.int8): 3, np.dtype(np.bool_): 9}[arr.dtype]
    return (pm.f_packed_ints(1, arr.shape) + pm.f_varint(2, dt)
            + pm.f_str(8, name) + pm.f_bytes(9, arr.tobytes()))


def _onnx_attr_i(name, v):
    return pm.f_str(1, name) + pm.f_varint(3, v) + pm.f_varint(20, 2)


def _onnx_attr_f(name, v):
    return pm.f_str(1, name) + pm.f_float(2, v) + pm.f_varint(20, 1)


def _onnx_attr_ints(name, vals):
    return pm.f_str(1, name) + pm.f_packed_ints(8, vals) + pm.f_varint(20, 7)


def _onnx_node(op_type, inputs, outputs, *attrs):
    b = b"".join(pm.f_str(1, i) for i in inputs)
    b += b"".join(pm.f_str(2, o) for o in outputs)
    b += pm.f_str(4, op_type)
    b += b"".join(pm.f_bytes(5, a) for a in attrs)
    return b


def _onnx_input(name, shape):
    dims = b"".join(pm.f_bytes(1, pm.f_varint(1, d)) for d in shape)
    tensor_type = pm.f_varint(1, 1) + pm.f_bytes(2, dims)  # f32
    return pm.f_str(1, name) + pm.f_bytes(2, pm.f_bytes(1, tensor_type))


def _onnx_model(nodes, initializers, inputs, outputs):
    g = b"".join(pm.f_bytes(1, n) for n in nodes)
    g += pm.f_str(2, "g")
    g += b"".join(pm.f_bytes(5, i) for i in initializers)
    g += b"".join(pm.f_bytes(11, i) for i in inputs)
    g += b"".join(pm.f_bytes(12, pm.f_str(1, o)) for o in outputs)
    opset = pm.f_str(1, "") + pm.f_varint(2, 13)
    return pm.f_varint(1, 8) + pm.f_bytes(7, g) + pm.f_bytes(8, opset)


def _freeze_cf(fn, feeds, lower: bool):
    """Like _freeze but with explicit control over control-flow lowering:
    lower=True produces TF1 Switch/Merge/Enter/Exit frames, lower=False keeps
    the V2 functional While/If ops + FunctionDef library."""
    conc = tf.function(fn).get_concrete_function(
        *[tf.TensorSpec(v.shape, v.dtype) for v in feeds])
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    frozen = convert_variables_to_constants_v2(conc, lower_control_flow=lower)
    gd = frozen.graph.as_graph_def()
    golden = [np.asarray(t) for t in frozen(*[tf.constant(v) for v in feeds])]
    in_names = [i.name.split(":")[0] for i in frozen.inputs]
    out_names = [o.name for o in frozen.outputs]
    return gd, golden, in_names, out_names


class TestTFControlFlow:
    """TFGraphMapper.java / AbstractSession control-flow parity (VERDICT r1
    missing #1): both the TF1 dataflow frames and the TF2 functional ops,
    golden-tested against TF's own execution."""

    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1-frames", "v2-functional"])
    def test_while_loop(self, rng, lower):
        def loopy(x):
            i = tf.constant(0)

            def cond(i, acc):
                return i < 5

            def body(i, acc):
                return i + 1, acc * 1.5 + 1.0

            _, out = tf.while_loop(cond, body, [i, x])
            return out

        x = rng.normal(size=(3, 4)).astype(np.float32)
        _golden_match(*_freeze_cf(loopy, [x], lower), [x])

    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1-switch-merge", "v2-if"])
    def test_cond(self, rng, lower):
        def condy(x):
            return tf.cond(tf.reduce_sum(x) > 0,
                           lambda: x * 2.0 + 1.0, lambda: x - 3.0)

        for sign in (1.0, -1.0):  # exercise both branches
            x = (sign * np.abs(rng.normal(size=(3, 4)))).astype(np.float32)
            _golden_match(*_freeze_cf(condy, [x], lower), [x])

    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1-frames", "v2-functional"])
    def test_dynamic_length_rnn(self, rng, lower):
        """A while-loop RNN whose iteration count is a runtime scalar input —
        the dynamic-length recurrent shape TF-import previously rejected."""
        W = tf.constant(rng.normal(size=(4, 6)).astype(np.float32) * 0.4)
        U = tf.constant(rng.normal(size=(6, 6)).astype(np.float32) * 0.4)

        def rnn(x, n):
            h0 = tf.zeros((tf.shape(x)[0], 6))

            def cond(i, h):
                return i < n

            def body(i, h):
                xt = tf.gather(x, i, axis=1)
                return i + 1, tf.tanh(tf.matmul(xt, W) + tf.matmul(h, U))

            _, h = tf.while_loop(cond, body, [tf.constant(0), h0])
            return h

        xs = rng.normal(size=(2, 7, 4)).astype(np.float32)
        for n in (np.int32(5), np.int32(7)):  # genuinely dynamic trip count
            _golden_match(*_freeze_cf(rnn, [xs, tf.constant(n)], lower),
                          [xs, n])

    def test_partitioned_call_inlined(self, rng):
        @tf.function
        def inner(a):
            return tf.nn.relu(a) * 2.0

        def outer(x):
            return inner(x) + 1.0

        x = rng.normal(size=(3, 4)).astype(np.float32)
        gd, golden, in_names, out_names = _freeze_cf(outer, [x], lower=False)
        ops = {n.op for n in gd.node}
        if "PartitionedCall" in ops or "StatefulPartitionedCall" in ops:
            _golden_match(gd, golden, in_names, out_names, [x])
        else:  # TF already inlined it; still a valid golden check
            _golden_match(gd, golden, in_names, out_names, [x])

    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1-frames", "v2-functional"])
    def test_real_keras_lstm_graph(self, rng, lower):
        """A REAL tf.keras LSTM frozen graph (TensorList accumulators inside
        the while loop — the exact shape TF-import previously rejected)."""
        m = tf.keras.Sequential([
            tf.keras.layers.Input((7, 5)),
            tf.keras.layers.LSTM(6, return_sequences=True),
        ])
        conc = tf.function(lambda x: m(x)).get_concrete_function(
            tf.TensorSpec((3, 7, 5), tf.float32))
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        frozen = convert_variables_to_constants_v2(
            conc, lower_control_flow=lower)
        gd = frozen.graph.as_graph_def()
        x = rng.normal(size=(3, 7, 5)).astype(np.float32)
        golden = [np.asarray(t) for t in frozen(tf.constant(x))]
        in_names = [i.name.split(":")[0] for i in frozen.inputs]
        out_names = [o.name for o in frozen.outputs]
        _golden_match(gd, golden, in_names, out_names, [x])

    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1-frames", "v2-functional"])
    def test_nested_while(self, rng, lower):
        """Loop-in-loop (beam-search shape) — VERDICT r2 missing #4: nested
        V1 frames are detected recursively and each level lowers to its own
        lax.while_loop."""
        def nested(x):
            def outer_body(i, acc):
                def inner_body(j, a):
                    return j + 1, a * 0.5 + tf.cast(j, tf.float32)

                _, acc2 = tf.while_loop(lambda j, a: j < 2, inner_body,
                                        [tf.constant(0), acc])
                return i + 1, acc2

            _, out = tf.while_loop(lambda i, a: i < 3, outer_body,
                                   [tf.constant(0), x])
            return out

        x = rng.normal(size=(2,)).astype(np.float32)
        _golden_match(*_freeze_cf(nested, [x], lower=lower), [x])

    def test_triple_nested_while(self, rng):
        """Three levels of V1 frames; the innermost reads an outer loop var."""
        def nested3(x):
            def b1(i, acc):
                def b2(j, a):
                    def b3(k, z):
                        return k + 1, z + tf.cast(i + j + k, tf.float32)

                    _, z2 = tf.while_loop(lambda k, z: k < 2, b3,
                                          [tf.constant(0), a])
                    return j + 1, z2

                _, a2 = tf.while_loop(lambda j, a: j < 2, b2,
                                      [tf.constant(0), acc])
                return i + 1, a2

            _, out = tf.while_loop(lambda i, a: i < 2, b1,
                                   [tf.constant(0), x])
            return out

        x = rng.normal(size=(3,)).astype(np.float32)
        _golden_match(*_freeze_cf(nested3, [x], lower=True), [x])

    def test_sequential_sibling_whiles(self, rng):
        """Two sequential loops where the second's init is the first's Exit —
        siblings, not nesting (the parent-resolution edge case)."""
        def seq(x):
            _, h = tf.while_loop(lambda i, a: i < 3,
                                 lambda i, a: (i + 1, a + 1.0),
                                 [tf.constant(0), x])
            _, out = tf.while_loop(lambda i, a: i < 2,
                                   lambda i, a: (i + 1, a * 2.0),
                                   [tf.constant(0), h])
            return out

        x = rng.normal(size=(2,)).astype(np.float32)
        _golden_match(*_freeze_cf(seq, [x], lower=True), [x])


class TestOnnxImport:
    def test_mlp_gemm_relu_softmax(self, rng):
        w1 = rng.normal(size=(4, 8)).astype(np.float32) * 0.3
        b1 = np.zeros(8, np.float32)
        w2 = rng.normal(size=(8, 3)).astype(np.float32) * 0.3
        model = _onnx_model(
            nodes=[
                _onnx_node("Gemm", ["x", "w1", "b1"], ["h"]),
                _onnx_node("Relu", ["h"], ["hr"]),
                _onnx_node("Gemm", ["hr", "w2"], ["logits"]),
                _onnx_node("Softmax", ["logits"], ["probs"], _onnx_attr_i("axis", -1)),
            ],
            initializers=[_onnx_tensor("w1", w1), _onnx_tensor("b1", b1),
                          _onnx_tensor("w2", w2)],
            inputs=[_onnx_input("x", (5, 4))],
            outputs=["probs"],
        )
        sd = import_onnx(model)
        x = rng.normal(size=(5, 4)).astype(np.float32)
        out = sd.output({"x": x}, ["probs"])["probs"]
        h = np.maximum(x @ w1 + b1, 0) @ w2
        e = np.exp(h - h.max(-1, keepdims=True))
        np.testing.assert_allclose(np.asarray(out), e / e.sum(-1, keepdims=True),
                                   atol=1e-5)

    def test_conv_pool_bn(self, rng):
        import torch
        import torch.nn.functional as F

        w = rng.normal(size=(4, 2, 3, 3)).astype(np.float32) * 0.3  # OIHW
        gamma = np.abs(rng.normal(size=4)).astype(np.float32) + 0.5
        beta = rng.normal(size=4).astype(np.float32)
        mean = rng.normal(size=4).astype(np.float32) * 0.1
        var = np.abs(rng.normal(size=4)).astype(np.float32) + 1.0
        model = _onnx_model(
            nodes=[
                _onnx_node("Conv", ["x", "w"], ["c"],
                           _onnx_attr_ints("strides", [1, 1]),
                           _onnx_attr_ints("pads", [1, 1, 1, 1]),
                           _onnx_attr_ints("kernel_shape", [3, 3])),
                _onnx_node("BatchNormalization",
                           ["c", "gamma", "beta", "mean", "var"], ["bn"],
                           _onnx_attr_f("epsilon", 1e-5)),
                _onnx_node("Relu", ["bn"], ["r"]),
                _onnx_node("MaxPool", ["r"], ["p"],
                           _onnx_attr_ints("kernel_shape", [2, 2]),
                           _onnx_attr_ints("strides", [2, 2])),
                _onnx_node("GlobalAveragePool", ["p"], ["g"]),
            ],
            initializers=[_onnx_tensor("w", w), _onnx_tensor("gamma", gamma),
                          _onnx_tensor("beta", beta), _onnx_tensor("mean", mean),
                          _onnx_tensor("var", var)],
            inputs=[_onnx_input("x", (2, 2, 8, 8))],
            outputs=["g"],
        )
        sd = import_onnx(model)
        x = rng.normal(size=(2, 2, 8, 8)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["g"])["g"])

        xt = torch.from_numpy(x)
        c = F.conv2d(xt, torch.from_numpy(w), padding=1)
        bn = F.batch_norm(c, torch.from_numpy(mean), torch.from_numpy(var),
                          torch.from_numpy(gamma), torch.from_numpy(beta),
                          training=False, eps=1e-5)
        p = F.max_pool2d(F.relu(bn), 2)
        ref = p.mean(dim=(2, 3), keepdim=True).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    def test_reduce_and_shape_ops(self, rng):
        model = _onnx_model(
            nodes=[
                _onnx_node("Transpose", ["x"], ["t"], _onnx_attr_ints("perm", [0, 2, 1])),
                _onnx_node("ReduceMean", ["t"], ["m"],
                           _onnx_attr_ints("axes", [2]), _onnx_attr_i("keepdims", 0)),
                _onnx_node("Concat", ["m", "m"], ["c"], _onnx_attr_i("axis", 1)),
            ],
            initializers=[],
            inputs=[_onnx_input("x", (2, 3, 4))],
            outputs=["c"],
        )
        sd = import_onnx(model)
        x = rng.normal(size=(2, 3, 4)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["c"])["c"])
        ref = np.transpose(x, (0, 2, 1)).mean(2)
        np.testing.assert_allclose(out, np.concatenate([ref, ref], 1), atol=1e-6)

    def test_flatten_dynamic_batch(self, rng):
        w1 = rng.normal(size=(6, 3)).astype(np.float32) * 0.3
        model = _onnx_model(
            nodes=[
                _onnx_node("Flatten", ["x"], ["f"]),
                _onnx_node("Gemm", ["f", "w1"], ["y"]),
            ],
            initializers=[_onnx_tensor("w1", w1)],
            inputs=[_onnx_input("x", (-1, 2, 3))],  # dynamic batch dim
            outputs=["y"],
        )
        sd = import_onnx(model)
        x = rng.normal(size=(5, 2, 3)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        np.testing.assert_allclose(out, x.reshape(5, 6) @ w1, atol=1e-5)

    def test_clip_omitted_optional_input(self, rng):
        hi = np.asarray(0.5, np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("Clip", ["x", "", "hi"], ["y"])],
            initializers=[_onnx_tensor("hi", hi.reshape(()))],
            inputs=[_onnx_input("x", (4,))],
            outputs=["y"],
        )
        sd = import_onnx(model)
        x = np.asarray([-2.0, 0.1, 0.4, 3.0], np.float32)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        np.testing.assert_allclose(out, np.minimum(x, 0.5), atol=1e-6)


def _onnx_attr_s(name, v):
    return pm.f_str(1, name) + pm.f_str(4, v) + pm.f_varint(20, 3)


def _torch_lstm_onnx_weights(lstm, bidirectional=False):
    """torch gate order [i,f,g,o] → ONNX [i,o,f,c]; stack directions."""
    import torch

    perm = [0, 3, 1, 2]

    def blocks(w, h):
        return np.concatenate([w[j * h:(j + 1) * h] for j in perm], axis=0)

    h = lstm.hidden_size
    Ws, Rs, Bs = [], [], []
    sufs = [""] + (["_reverse"] if bidirectional else [])
    for suf in sufs:
        wi = getattr(lstm, f"weight_ih_l0{suf}").detach().numpy()
        wh = getattr(lstm, f"weight_hh_l0{suf}").detach().numpy()
        bi = getattr(lstm, f"bias_ih_l0{suf}").detach().numpy()
        bh = getattr(lstm, f"bias_hh_l0{suf}").detach().numpy()
        Ws.append(blocks(wi, h))
        Rs.append(blocks(wh, h))
        Bs.append(np.concatenate([blocks(bi, h), blocks(bh, h)]))
    return (np.stack(Ws).astype(np.float32), np.stack(Rs).astype(np.float32),
            np.stack(Bs).astype(np.float32))


class TestOnnxRecurrent:
    """ONNX LSTM/GRU/RNN rules (VERDICT r1 missing #3), goldens from torch
    (same recurrences, CPU reference)."""

    def _run(self, model_bytes, feeds, outputs):
        sd = import_onnx(model_bytes)
        return sd.output(feeds, outputs)

    def test_lstm_matches_torch(self, rng):
        import torch

        T, B, I, H = 5, 3, 4, 6
        lstm = torch.nn.LSTM(I, H)
        W, R, Bb = _torch_lstm_onnx_weights(lstm)
        x = rng.normal(size=(T, B, I)).astype(np.float32)
        with torch.no_grad():
            y_t, (h_t, c_t) = lstm(torch.from_numpy(x))
        model = _onnx_model(
            nodes=[_onnx_node("LSTM", ["x", "W", "R", "B"], ["Y", "Yh", "Yc"],
                              _onnx_attr_i("hidden_size", H))],
            initializers=[_onnx_tensor("W", W), _onnx_tensor("R", R),
                          _onnx_tensor("B", Bb)],
            inputs=[_onnx_input("x", (T, B, I))], outputs=["Y", "Yh", "Yc"])
        res = self._run(model, {"x": x}, ["Y", "Yh", "Yc"])
        np.testing.assert_allclose(res["Y"][:, 0], y_t.numpy(), atol=1e-5)
        np.testing.assert_allclose(res["Yh"], h_t.numpy(), atol=1e-5)
        np.testing.assert_allclose(res["Yc"], c_t.numpy(), atol=1e-5)

    def test_lstm_bidirectional(self, rng):
        import torch

        T, B, I, H = 4, 2, 3, 5
        lstm = torch.nn.LSTM(I, H, bidirectional=True)
        W, R, Bb = _torch_lstm_onnx_weights(lstm, bidirectional=True)
        x = rng.normal(size=(T, B, I)).astype(np.float32)
        with torch.no_grad():
            y_t, _ = lstm(torch.from_numpy(x))  # (T,B,2H)
        model = _onnx_model(
            nodes=[_onnx_node("LSTM", ["x", "W", "R", "B"], ["Y"],
                              _onnx_attr_i("hidden_size", H),
                              _onnx_attr_s("direction", "bidirectional"))],
            initializers=[_onnx_tensor("W", W), _onnx_tensor("R", R),
                          _onnx_tensor("B", Bb)],
            inputs=[_onnx_input("x", (T, B, I))], outputs=["Y"])
        res = self._run(model, {"x": x}, ["Y"])  # (T,2,B,H)
        np.testing.assert_allclose(res["Y"][:, 0], y_t.numpy()[:, :, :H],
                                   atol=1e-5)
        np.testing.assert_allclose(res["Y"][:, 1], y_t.numpy()[:, :, H:],
                                   atol=1e-5)

    def test_gru_matches_torch(self, rng):
        import torch

        T, B, I, H = 5, 3, 4, 6
        gru = torch.nn.GRU(I, H)
        # torch order [r,z,n] → ONNX [z,r,h]; torch keeps recurrent bias
        # separate = linear_before_reset=1
        perm = [1, 0, 2]

        def blocks(w):
            return np.concatenate([w[j * H:(j + 1) * H] for j in perm], axis=0)

        W = np.stack([blocks(gru.weight_ih_l0.detach().numpy())])
        R = np.stack([blocks(gru.weight_hh_l0.detach().numpy())])
        Bb = np.stack([np.concatenate(
            [blocks(gru.bias_ih_l0.detach().numpy()),
             blocks(gru.bias_hh_l0.detach().numpy())])])
        x = rng.normal(size=(T, B, I)).astype(np.float32)
        with torch.no_grad():
            y_t, h_t = gru(torch.from_numpy(x))
        model = _onnx_model(
            nodes=[_onnx_node("GRU", ["x", "W", "R", "B"], ["Y", "Yh"],
                              _onnx_attr_i("hidden_size", H),
                              _onnx_attr_i("linear_before_reset", 1))],
            initializers=[_onnx_tensor("W", W.astype(np.float32)),
                          _onnx_tensor("R", R.astype(np.float32)),
                          _onnx_tensor("B", Bb.astype(np.float32))],
            inputs=[_onnx_input("x", (T, B, I))], outputs=["Y", "Yh"])
        res = self._run(model, {"x": x}, ["Y", "Yh"])
        np.testing.assert_allclose(res["Y"][:, 0], y_t.numpy(), atol=1e-5)
        np.testing.assert_allclose(res["Yh"], h_t.numpy(), atol=1e-5)

    def test_simple_rnn_matches_torch(self, rng):
        import torch

        T, B, I, H = 5, 2, 3, 4
        rnn = torch.nn.RNN(I, H)
        W = np.stack([rnn.weight_ih_l0.detach().numpy()]).astype(np.float32)
        R = np.stack([rnn.weight_hh_l0.detach().numpy()]).astype(np.float32)
        Bb = np.stack([np.concatenate(
            [rnn.bias_ih_l0.detach().numpy(),
             rnn.bias_hh_l0.detach().numpy()])]).astype(np.float32)
        x = rng.normal(size=(T, B, I)).astype(np.float32)
        with torch.no_grad():
            y_t, h_t = rnn(torch.from_numpy(x))
        model = _onnx_model(
            nodes=[_onnx_node("RNN", ["x", "W", "R", "B"], ["Y", "Yh"],
                              _onnx_attr_i("hidden_size", H))],
            initializers=[_onnx_tensor("W", W), _onnx_tensor("R", R),
                          _onnx_tensor("B", Bb)],
            inputs=[_onnx_input("x", (T, B, I))], outputs=["Y", "Yh"])
        res = self._run(model, {"x": x}, ["Y", "Yh"])
        np.testing.assert_allclose(res["Y"][:, 0], y_t.numpy(), atol=1e-5)
        np.testing.assert_allclose(res["Yh"], h_t.numpy(), atol=1e-5)

    def test_lstm_dynamic_batch(self, rng):
        """Dynamic batch dims accepted (VERDICT r1 weak #5): one import, two
        batch sizes."""
        import torch

        T, I, H = 4, 3, 5
        lstm = torch.nn.LSTM(I, H)
        W, R, Bb = _torch_lstm_onnx_weights(lstm)
        model = _onnx_model(
            nodes=[_onnx_node("LSTM", ["x", "W", "R", "B"], ["Y"],
                              _onnx_attr_i("hidden_size", H))],
            initializers=[_onnx_tensor("W", W), _onnx_tensor("R", R),
                          _onnx_tensor("B", Bb)],
            inputs=[_onnx_input("x", (T, -1, I))], outputs=["Y"])
        sd = import_onnx(model)
        for B in (2, 7):
            x = rng.normal(size=(T, B, I)).astype(np.float32)
            with torch.no_grad():
                y_t, _ = lstm(torch.from_numpy(x))
            res = sd.output({"x": x}, ["Y"])
            np.testing.assert_allclose(res["Y"][:, 0], y_t.numpy(), atol=1e-5)

    def test_imported_lstm_classifier_finetunes(self, rng):
        """ONNX LSTM + Gemm head imports and fine-tunes (grads flow through
        the scan)."""
        import torch
        from deeplearning4j_tpu.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Adam

        T, I, H, C = 6, 4, 8, 2
        lstm = torch.nn.LSTM(I, H)
        W, R, Bb = _torch_lstm_onnx_weights(lstm)
        wo = (rng.normal(size=(H, C)) * 0.4).astype(np.float32)
        bo = np.zeros(C, np.float32)
        model = _onnx_model(
            nodes=[
                # layout=1 (batch-major): sd.fit slices minibatches on axis 0
                _onnx_node("LSTM", ["x", "W", "R", "B"], ["Y", "Yh"],
                           _onnx_attr_i("hidden_size", H),
                           _onnx_attr_i("layout", 1)),
                _onnx_node("Squeeze", ["Yh"], ["h"],  # (B,D,H) -> (B,H)
                           _onnx_attr_ints("axes", [1])),
                _onnx_node("Gemm", ["h", "wo", "bo"], ["logits"]),
            ],
            initializers=[_onnx_tensor("W", W), _onnx_tensor("R", R),
                          _onnx_tensor("B", Bb), _onnx_tensor("wo", wo),
                          _onnx_tensor("bo", bo)],
            inputs=[_onnx_input("x", (-1, T, I))], outputs=["logits"])
        sd = import_onnx(model)
        weight_names = [n for n in sd._arrays
                        if n in ("W", "R", "B", "wo", "bo")]
        sd.convert_to_variable(*weight_names)
        logits = sd.get_variable(sd.tf_name_map["logits:0"]
                                 if hasattr(sd, "tf_name_map") else "logits")
        y = sd.placeholder("y", shape=(-1, C))
        loss = sd.loss.softmaxCrossEntropy(logits, y)
        sd.set_loss_variables(loss)
        sd.set_training_config(TrainingConfig(
            updater=Adam(0.02),
            data_set_feature_mapping=["x"],
            data_set_label_mapping=["y"]))
        # toy task: class = sign of the mean of the first feature
        xs = rng.normal(size=(32, T, I)).astype(np.float32)
        labels = np.eye(C, dtype=np.float32)[
            (xs[:, :, 0].mean(axis=1) > 0).astype(int)]
        hist = sd.fit((xs, labels), epochs=40)
        assert hist[-1] < hist[0] * 0.6, (hist[0], hist[-1])


class TestTFImportFineTune:
    """BASELINE config #4 path: import a frozen TF transformer graph into
    SameDiff, convert its weights to variables, and fine-tune."""

    def test_imported_transformer_finetunes(self, rng):
        import tensorflow as tf
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )
        from deeplearning4j_tpu.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Adam

        V, H, T, C = 20, 8, 6, 2
        emb = tf.Variable(rng.normal(size=(V, H)).astype(np.float32) * 0.2)
        wq = tf.Variable(rng.normal(size=(H, H)).astype(np.float32) * 0.3)
        wv = tf.Variable(rng.normal(size=(H, H)).astype(np.float32) * 0.3)
        wo = tf.Variable(rng.normal(size=(H, C)).astype(np.float32) * 0.3)

        def model(ids):
            h = tf.gather(emb, ids)                      # (B,T,H)
            q = tf.matmul(h, wq)
            s = tf.matmul(q, q, transpose_b=True) / np.sqrt(H).astype(np.float32)
            a = tf.matmul(tf.nn.softmax(s), tf.matmul(h, wv))
            cls = (h + a)[:, 0]                          # residual, [CLS]
            return tf.matmul(cls, wo)                    # logits

        conc = tf.function(model).get_concrete_function(
            tf.TensorSpec((None, T), tf.int32))
        frozen = convert_variables_to_constants_v2(conc)
        sd = import_graph_def(frozen.graph.as_graph_def())

        # weights imported as constants → make them trainable
        weight_names = [n for n, v in sd._arrays.items()
                        if np.asarray(v).ndim == 2]
        sd.convert_to_variable(*weight_names)
        assert set(sd.trainable_names()) == set(weight_names)

        logits_name = sd.tf_name_map[frozen.outputs[0].name]
        logits = sd.get_variable(logits_name)
        y = sd.placeholder("y", shape=(-1, C))
        loss = sd.loss.softmaxCrossEntropy(logits, y)
        sd.set_loss_variables(loss)
        in_name = frozen.inputs[0].name.split(":")[0]
        sd.set_training_config(TrainingConfig(
            updater=Adam(0.01),
            data_set_feature_mapping=[in_name],
            data_set_label_mapping=["y"]))

        # toy task: class = (first token < V//2)
        ids = rng.integers(0, V, size=(64, T)).astype(np.int32)
        labels = np.eye(C, dtype=np.float32)[(ids[:, 0] < V // 2).astype(int)]
        hist = sd.fit((ids, labels), epochs=40)
        assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])


class TestTFBatchNormTraining:
    """FusedBatchNormV3 training-mode import (VERDICT r2 missing #1).

    Reference parity: samediff-import FusedBatchNormV3 rule maps BOTH modes
    (path-cite, mount empty). Here is_training=true routes onto the registry's
    fused-VJP ``batchnorm_train`` op, so imported conv nets fine-tune through
    BN with batch statistics; forward AND one optimizer step are golden-tested
    against TF itself.
    """

    def _arrays(self, rng):
        w = (rng.normal(size=(3, 3, 2, 4)) * 0.4).astype(np.float32)
        gamma = rng.uniform(0.5, 1.5, size=4).astype(np.float32)
        beta = (rng.normal(size=4) * 0.1).astype(np.float32)
        rm = rng.normal(size=4).astype(np.float32)
        rv = rng.uniform(0.5, 1.5, size=4).astype(np.float32)
        wo = (rng.normal(size=(4, 3)) * 0.5).astype(np.float32)
        return w, gamma, beta, rm, rv, wo

    def test_forward_golden(self, rng):
        w, gamma, beta, rm, rv, wo = map(tf.constant, self._arrays(rng))

        def net(x):
            h = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            h, bm, bv = tf.compat.v1.nn.fused_batch_norm(
                h, gamma, beta, mean=rm, variance=rv, epsilon=1e-3,
                is_training=True)
            h = tf.nn.relu(h)
            h = tf.reduce_mean(h, axis=[1, 2])
            return tf.matmul(h, wo), bm, bv

        x = rng.normal(size=(4, 8, 8, 2)).astype(np.float32)
        # all three outputs checked: y, batch_mean, batch_variance (unbiased)
        _golden_match(*_freeze(net, [x]), [x], atol=1e-4)

    def test_exponential_avg_factor_blend(self, rng):
        """V3 running-stat blend: out = (1-f)*old + f*batch."""
        w, gamma, beta, rm, rv, _ = map(tf.constant, self._arrays(rng))

        def net(x):
            h = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y, bm, bv = tf.compat.v1.nn.fused_batch_norm(
                h, gamma, beta, mean=rm, variance=rv, epsilon=1e-3,
                is_training=True, exponential_avg_factor=0.3)
            return y, bm, bv

        x = rng.normal(size=(4, 8, 8, 2)).astype(np.float32)
        _golden_match(*_freeze(net, [x]), [x], atol=1e-4)

    def test_exponential_avg_factor_zero(self, rng):
        """Explicit f=0.0 (freeze-running-stats pattern): TF returns the
        incoming running stats unchanged; 0.0 must not collapse to the 1.0
        default (falsy-zero regression)."""
        w, gamma, beta, rm, rv, _ = map(tf.constant, self._arrays(rng))

        def net(x):
            h = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y, bm, bv = tf.compat.v1.nn.fused_batch_norm(
                h, gamma, beta, mean=rm, variance=rv, epsilon=1e-3,
                is_training=True, exponential_avg_factor=0.0)
            return y, bm, bv

        x = rng.normal(size=(4, 8, 8, 2)).astype(np.float32)
        _golden_match(*_freeze(net, [x]), [x], atol=1e-4)

    def test_finetune_one_step_matches_tf(self, rng):
        """Import → convert weights to variables → one SGD step == TF's
        GradientTape step through training-mode BN (grads flow through the
        batch statistics, not frozen running stats)."""
        from deeplearning4j_tpu.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Sgd

        w, gamma, beta, rm, rv, wo = self._arrays(rng)
        x = rng.normal(size=(8, 8, 8, 2)).astype(np.float32)
        labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=8)]
        lr = 0.5

        # --- TF golden: one tape step on the same net
        vw, vg, vb, vwo = (tf.Variable(a) for a in (w, gamma, beta, wo))

        def logits_fn(xt):
            h = tf.nn.conv2d(xt, vw, strides=1, padding="SAME")
            h, _, _ = tf.compat.v1.nn.fused_batch_norm(
                h, vg, vb, mean=tf.constant(rm), variance=tf.constant(rv),
                epsilon=1e-3, is_training=True)
            h = tf.nn.relu(h)
            h = tf.reduce_mean(h, axis=[1, 2])
            return tf.matmul(h, vwo)

        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.nn.softmax_cross_entropy_with_logits(
                labels=tf.constant(labels), logits=logits_fn(tf.constant(x))))
        grads = tape.gradient(loss, [vw, vg, vb, vwo])
        expected = [v - lr * g for v, g in zip((w, gamma, beta, wo), grads)]

        # --- import the frozen graph and take the same step
        conc = tf.function(logits_fn).get_concrete_function(
            tf.TensorSpec((None, 8, 8, 2), tf.float32))
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )
        frozen = convert_variables_to_constants_v2(conc)
        sd = import_graph_def(frozen.graph.as_graph_def())

        # locate the imported constants by value; rm/rv stay frozen constants
        name_of = {}
        for n, arr in sd._arrays.items():
            for key, ref in (("w", w), ("gamma", gamma), ("beta", beta),
                             ("wo", wo)):
                a = np.asarray(arr)
                if a.shape == ref.shape and np.allclose(a, ref):
                    name_of[key] = n
        assert len(name_of) == 4, name_of
        sd.convert_to_variable(*name_of.values())

        logits = sd.get_variable(sd.tf_name_map[frozen.outputs[0].name])
        y = sd.placeholder("y", shape=(-1, 3))
        sd.set_loss_variables(sd.loss.softmaxCrossEntropy(logits, y))
        in_name = frozen.inputs[0].name.split(":")[0]
        sd.set_training_config(TrainingConfig(
            updater=Sgd(lr),
            data_set_feature_mapping=[in_name],
            data_set_label_mapping=["y"]))
        sd.fit((x, labels), epochs=1)

        for key, exp in zip(("w", "gamma", "beta", "wo"), expected):
            np.testing.assert_allclose(
                sd._arrays[name_of[key]], np.asarray(exp),
                atol=2e-4, rtol=1e-3, err_msg=key)

    def test_imported_bn_convnet_finetunes(self, rng):
        """End-to-end: a conv+BN net with training-mode BN imports and the
        loss drops over a short fine-tune (the VERDICT r2 'done' criterion)."""
        from deeplearning4j_tpu.samediff import TrainingConfig
        from deeplearning4j_tpu.nn.updaters import Adam

        w, gamma, beta, rm, rv, wo = self._arrays(rng)
        tw, tg, tb, two = map(tf.constant, (w, gamma, beta, wo))

        def net(xt):
            h = tf.nn.conv2d(xt, tw, strides=1, padding="SAME")
            h, _, _ = tf.compat.v1.nn.fused_batch_norm(
                h, tg, tb, mean=tf.constant(rm), variance=tf.constant(rv),
                epsilon=1e-3, is_training=True)
            h = tf.nn.relu(h)
            h = tf.reduce_mean(h, axis=[1, 2])
            return tf.matmul(h, two)

        conc = tf.function(net).get_concrete_function(
            tf.TensorSpec((None, 8, 8, 2), tf.float32))
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )
        frozen = convert_variables_to_constants_v2(conc)
        sd = import_graph_def(frozen.graph.as_graph_def())
        weight_names = [n for n, v in sd._arrays.items()
                        if np.asarray(v).ndim in (2, 4)]
        sd.convert_to_variable(*weight_names)

        logits = sd.get_variable(sd.tf_name_map[frozen.outputs[0].name])
        y = sd.placeholder("y", shape=(-1, 3))
        sd.set_loss_variables(sd.loss.softmaxCrossEntropy(logits, y))
        in_name = frozen.inputs[0].name.split(":")[0]
        sd.set_training_config(TrainingConfig(
            updater=Adam(0.05),
            data_set_feature_mapping=[in_name],
            data_set_label_mapping=["y"]))
        xs = rng.normal(size=(32, 8, 8, 2)).astype(np.float32)
        ys = np.eye(3, dtype=np.float32)[
            (xs.mean(axis=(1, 2, 3)) > 0).astype(int)]
        hist = sd.fit((xs, ys), epochs=30)
        assert hist[-1] < hist[0] * 0.6, (hist[0], hist[-1])


def _onnx_attr_s(name, v):
    return pm.f_str(1, name) + pm.f_bytes(4, v.encode()) + pm.f_varint(20, 3)


class TestOnnxRound3Rules:
    """Round-3 ONNX breadth (93 rules): shape/indexing, ConvTranspose,
    InstanceNorm, Resize, reductions — goldens vs torch/numpy."""

    def test_slice_pad_tile_expand(self, rng):
        model = _onnx_model(
            nodes=[
                _onnx_node("Slice", ["x", "st", "en", "ax", "sp"], ["s"]),
                _onnx_node("Pad", ["s", "pads"], ["p"]),
                _onnx_node("Tile", ["p", "reps"], ["t"]),
                _onnx_node("Expand", ["t", "eshape"], ["e"]),
            ],
            initializers=[
                _onnx_tensor("st", np.asarray([1], np.int64)),
                _onnx_tensor("en", np.asarray([5], np.int64)),
                _onnx_tensor("ax", np.asarray([1], np.int64)),
                _onnx_tensor("sp", np.asarray([2], np.int64)),
                _onnx_tensor("pads", np.asarray([0, 1, 0, 1], np.int64)),
                _onnx_tensor("reps", np.asarray([2, 1], np.int64)),
                _onnx_tensor("eshape", np.asarray([4, 4], np.int64)),
            ],
            inputs=[_onnx_input("x", (2, 6))], outputs=["e"])
        sd = import_onnx(model)
        x = rng.normal(size=(2, 6)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["e"])["e"])
        ref = x[:, 1:5:2]
        ref = np.pad(ref, [(0, 0), (1, 1)])
        ref = np.tile(ref, (2, 1))
        ref = np.broadcast_to(ref, (4, 4))
        np.testing.assert_allclose(out, ref, atol=1e-6)

    def test_split_argmax_cumsum_onehot(self, rng):
        model = _onnx_model(
            nodes=[
                _onnx_node("Split", ["x"], ["a", "b"], _onnx_attr_i("axis", 1),
                           _onnx_attr_ints("split", [2, 2])),
                _onnx_node("ArgMax", ["a"], ["am"], _onnx_attr_i("axis", 1),
                           _onnx_attr_i("keepdims", 0)),
                _onnx_node("OneHot", ["am", "depth", "vals"], ["oh"]),
                _onnx_node("CumSum", ["b", "cax"], ["cs"]),
            ],
            initializers=[
                _onnx_tensor("depth", np.asarray([2], np.int64)),
                _onnx_tensor("vals", np.asarray([0.0, 1.0], np.float32)),
                _onnx_tensor("cax", np.asarray([1], np.int64)),
            ],
            inputs=[_onnx_input("x", (3, 4))], outputs=["oh", "cs"])
        sd = import_onnx(model)
        x = rng.normal(size=(3, 4)).astype(np.float32)
        res = sd.output({"x": x}, [sd.tf_name_map.get("oh", "oh")
                                   if hasattr(sd, "tf_name_map") else "oh",
                                   "cs"])
        a, b = x[:, :2], x[:, 2:]
        np.testing.assert_allclose(np.asarray(res["oh"]),
                                   np.eye(2)[a.argmax(1)], atol=1e-6)
        np.testing.assert_allclose(np.asarray(res["cs"]),
                                   np.cumsum(b, axis=1), atol=1e-5)

    def test_conv_transpose_matches_torch(self, rng):
        import torch
        import torch.nn.functional as F

        w = rng.normal(size=(2, 3, 2, 2)).astype(np.float32) * 0.4  # IOHW
        model = _onnx_model(
            nodes=[_onnx_node("ConvTranspose", ["x", "w"], ["y"],
                              _onnx_attr_ints("strides", [2, 2]),
                              _onnx_attr_ints("kernel_shape", [2, 2]))],
            initializers=[_onnx_tensor("w", w)],
            inputs=[_onnx_input("x", (1, 2, 4, 4))], outputs=["y"])
        sd = import_onnx(model)
        x = rng.normal(size=(1, 2, 4, 4)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        ref = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                                 stride=2).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    def test_instance_norm_matches_torch(self, rng):
        import torch
        import torch.nn.functional as F

        g = (rng.normal(size=3) * 0.3 + 1).astype(np.float32)
        b = rng.normal(size=3).astype(np.float32)
        model = _onnx_model(
            nodes=[_onnx_node("InstanceNormalization", ["x", "g", "b"], ["y"],
                              _onnx_attr_f("epsilon", 1e-5))],
            initializers=[_onnx_tensor("g", g), _onnx_tensor("b", b)],
            inputs=[_onnx_input("x", (2, 3, 5, 5))], outputs=["y"])
        sd = import_onnx(model)
        x = rng.normal(size=(2, 3, 5, 5)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        ref = F.instance_norm(torch.from_numpy(x),
                              weight=torch.from_numpy(g),
                              bias=torch.from_numpy(b), eps=1e-5).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    def test_depth_space_roundtrip_and_resize(self, rng):
        model = _onnx_model(
            nodes=[
                _onnx_node("SpaceToDepth", ["x"], ["s"],
                           _onnx_attr_i("blocksize", 2)),
                _onnx_node("DepthToSpace", ["s"], ["d"],
                           _onnx_attr_i("blocksize", 2),
                           _onnx_attr_s("mode", "DCR")),
                _onnx_node("Resize", ["d", "", "scales"], ["r"],
                           _onnx_attr_s("mode", "nearest")),
            ],
            initializers=[_onnx_tensor(
                "scales", np.asarray([1.0, 1.0, 2.0, 2.0], np.float32))],
            inputs=[_onnx_input("x", (1, 2, 4, 4))], outputs=["r"])
        sd = import_onnx(model)
        x = rng.normal(size=(1, 2, 4, 4)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["r"])["r"])
        ref = np.repeat(np.repeat(x, 2, axis=2), 2, axis=3)  # s2d∘d2s = id
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_topk_gather_elements_scatternd(self, rng):
        model = _onnx_model(
            nodes=[
                _onnx_node("TopK", ["x", "k"], ["v", "i"]),
                _onnx_node("GatherElements", ["x", "i"], ["g"],
                           _onnx_attr_i("axis", 1)),
            ],
            initializers=[_onnx_tensor("k", np.asarray([2], np.int64))],
            inputs=[_onnx_input("x", (3, 5))], outputs=["v", "g"])
        sd = import_onnx(model)
        x = rng.normal(size=(3, 5)).astype(np.float32)
        res = sd.output({"x": x}, ["v", "g"])
        want = np.sort(x, axis=1)[:, ::-1][:, :2]
        np.testing.assert_allclose(np.asarray(res["v"]), want, atol=1e-6)
        np.testing.assert_allclose(np.asarray(res["g"]), want, atol=1e-6)

    def test_conv_transpose_with_padding_matches_torch(self, rng):
        """DCGAN shape: k=4 s=2 p=1 — the ONNX pads→(k-1-p) mapping
        (review fix; direct pads pass-through only coincides at p=(k-1)/2)."""
        import torch
        import torch.nn.functional as F

        w = rng.normal(size=(2, 3, 4, 4)).astype(np.float32) * 0.3
        model = _onnx_model(
            nodes=[_onnx_node("ConvTranspose", ["x", "w"], ["y"],
                              _onnx_attr_ints("strides", [2, 2]),
                              _onnx_attr_ints("pads", [1, 1, 1, 1]),
                              _onnx_attr_ints("kernel_shape", [4, 4]))],
            initializers=[_onnx_tensor("w", w)],
            inputs=[_onnx_input("x", (1, 2, 4, 4))], outputs=["y"])
        sd = import_onnx(model)
        x = rng.normal(size=(1, 2, 4, 4)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        ref = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                                 stride=2, padding=1).numpy()
        assert out.shape == ref.shape == (1, 3, 8, 8)
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)

    def test_pad_with_axes_input(self, rng):
        """Opset-18 Pad axes: pads cover only the named axes (review fix)."""
        model = _onnx_model(
            nodes=[_onnx_node("Pad", ["x", "pads", "", "axes"], ["y"])],
            initializers=[
                _onnx_tensor("pads", np.asarray([1, 1], np.int64)),
                _onnx_tensor("axes", np.asarray([1], np.int64)),
            ],
            inputs=[_onnx_input("x", (2, 3))], outputs=["y"])
        sd = import_onnx(model)
        x = rng.normal(size=(2, 3)).astype(np.float32)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        assert out.shape == (2, 5)  # axis 0 untouched
        np.testing.assert_allclose(out, np.pad(x, [(0, 0), (1, 1)]),
                                   atol=1e-6)

    def test_resize_rejects_align_corners(self, rng):
        model = _onnx_model(
            nodes=[_onnx_node("Resize", ["x", "", "scales"], ["y"],
                              _onnx_attr_s("mode", "linear"),
                              _onnx_attr_s("coordinate_transformation_mode",
                                           "align_corners"))],
            initializers=[_onnx_tensor(
                "scales", np.asarray([1.0, 1.0, 2.0, 2.0], np.float32))],
            inputs=[_onnx_input("x", (1, 2, 4, 4))], outputs=["y"])
        with pytest.raises(NotImplementedError, match="align_corners"):
            import_onnx(model)


class TestRealTransformerGraph:
    """A real tf.keras MultiHeadAttention transformer block as a FROZEN
    GraphDef (the BERT-config import path with keras's actual lowering —
    Einsum projections, BatchMatMul-style attention)."""

    def test_keras_mha_block_imports(self, rng):
        H, heads = 8, 2
        inp = tf.keras.Input((6, H))
        att = tf.keras.layers.MultiHeadAttention(
            num_heads=heads, key_dim=H // heads)(inp, inp)
        h = tf.keras.layers.LayerNormalization()(inp + att)
        f = tf.keras.layers.Dense(H * 2, activation="gelu")(h)
        f = tf.keras.layers.Dense(H)(f)
        out = tf.keras.layers.LayerNormalization()(h + f)
        model = tf.keras.Model(inp, out)

        x = rng.normal(size=(2, 6, H)).astype(np.float32)
        _golden_match(*_freeze(lambda t: model(t), [x]), [x], atol=1e-4)

    def test_imported_mha_graph_serializes(self, rng, tmp_path):
        """Einsum lowers to a REGISTERED op, so imported transformers
        round-trip through save/load (review fix — custom_op would not)."""
        H = 8
        inp = tf.keras.Input((4, H))
        att = tf.keras.layers.MultiHeadAttention(num_heads=2, key_dim=4)(
            inp, inp)
        model = tf.keras.Model(inp, att)
        gd, golden, in_names, out_names = _freeze(lambda t: model(t), [
            rng.normal(size=(2, 4, H)).astype(np.float32)])
        sd = import_graph_def(gd)
        path = str(tmp_path / "mha.sd")
        sd.save(path)
        from deeplearning4j_tpu.samediff import SameDiff

        sd2 = SameDiff.load(path)
        x = rng.normal(size=(2, 4, H)).astype(np.float32)
        key = sd.tf_name_map[out_names[0]]
        a = np.asarray(sd.output({in_names[0]: x}, [key])[key])
        b = np.asarray(sd2.output({in_names[0]: x}, [key])[key])
        np.testing.assert_allclose(a, b, atol=1e-6)


class TestTFRuleTail:
    """Round-3 TF rule tail (165 op types): cumulative/scatter/segment/
    image/shape ops, golden-tested vs TF."""

    def test_cumulative_argmin_topk(self, rng):
        def fn(x):
            c = tf.cumsum(x, axis=1)
            p = tf.math.cumprod(x, axis=0)
            am = tf.argmin(x, axis=1)
            v, i = tf.math.top_k(x, k=2)
            return c, p, am, v, i

        x = rng.normal(size=(3, 5)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_scatter_gather_nd_segment(self, rng):
        def fn(x):
            idx = tf.constant([[0], [2]])
            sc = tf.scatter_nd(idx, x[:2], tf.constant([4, 4]))
            tsu = tf.tensor_scatter_nd_update(x, idx, x[:2] * 2.0)
            gn = tf.gather_nd(x, tf.constant([[1, 2], [3, 0]]))
            seg = tf.math.unsorted_segment_sum(
                x, tf.constant([0, 1, 0, 1]), 2)
            return sc, tsu, gn, seg

        x = rng.normal(size=(4, 4)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_reverse_roll_broadcast_like(self, rng):
        def fn(x):
            r = tf.reverse(x, axis=[1])
            rs = tf.reverse_sequence(x, tf.constant([2, 3]), seq_axis=1,
                                     batch_axis=0)
            ro = tf.roll(x, shift=[1], axis=[0])
            b = tf.broadcast_to(x[:1], tf.constant([2, 4]))
            z = tf.zeros_like(x) + tf.ones_like(x)
            return r, rs, ro, b, z

        x = rng.normal(size=(2, 4)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_depth_space_patches_lrn_leaky(self, rng):
        def fn(x):
            d = tf.nn.space_to_depth(x, 2)
            u = tf.nn.depth_to_space(d, 2)
            p = tf.image.extract_patches(x, sizes=[1, 2, 2, 1],
                                         strides=[1, 2, 2, 1],
                                         rates=[1, 1, 1, 1], padding="VALID")
            n = tf.nn.lrn(x, depth_radius=1, bias=1.0, alpha=0.5, beta=0.4)
            lk = tf.nn.leaky_relu(x[..., 0], alpha=0.0)  # explicit 0 honored
            return d, u, p, n, lk

        x = rng.normal(size=(1, 4, 4, 4)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x], atol=1e-4)

    def test_band_bincount_invperm_linspace(self, rng):
        def fn(x):
            bp = tf.linalg.band_part(x, 1, 0)
            ip = tf.math.invert_permutation(tf.constant([2, 0, 1, 3]))
            ls = tf.raw_ops.LinSpace(start=0.0, stop=1.0, num=5)
            fm = tf.math.floormod(x, 2.0)
            return bp, ip, ls, fm

        x = rng.normal(size=(4, 4)).astype(np.float32)
        _golden_match(*_freeze(fn, [x]), [x])

    def test_mod_truncdiv_bincount_semantics(self, rng):
        """Raw Mod is truncation (sign of dividend); TruncateDiv keeps int
        dtype; Bincount DROPS values >= size and honors weights
        (review fixes)."""
        def fn(a, b, v):
            m_ = tf.raw_ops.Mod(x=a, y=b)
            td = tf.raw_ops.TruncateDiv(x=tf.cast(a, tf.int32),
                                        y=tf.cast(b, tf.int32))
            bc = tf.raw_ops.Bincount(arr=v, size=3,
                                     weights=tf.constant([], tf.float32))
            bw = tf.raw_ops.Bincount(arr=v, size=3,
                                     weights=tf.constant(
                                         [0.5, 2.0, 1.0, 4.0], tf.float32))
            return m_, td, bc, bw

        a = np.asarray([-7.0, 7.0, -7.0], np.float32)
        b = np.asarray([3.0, -3.0, -3.0], np.float32)
        v = np.asarray([0, 1, 5, 1], np.int32)  # 5 is out of range -> dropped
        _golden_match(*_freeze(fn, [a, b, v]), [a, b, v])


class TestTFExplicitGradientGraphs:
    """tf.gradients-exported TRAINING graphs (VERDICT r3 missing #2): the
    frozen graph CONTAINS the backward pass as explicit *Grad kernels
    (ReluGrad, FusedBatchNormGradV3, Conv2DBackprop*, MaxPoolGrad...).
    Import must reproduce TF's loss AND gradients, and a one-step SGD
    update applied from the imported gradients must match TF's update."""

    def _build_step(self, rng):
        tf.keras.utils.set_random_seed(3)
        w1 = tf.Variable(tf.random.normal((3, 3, 3, 8), stddev=0.2))
        gamma = tf.Variable(tf.ones(8))
        beta = tf.Variable(tf.zeros(8))
        w2 = tf.Variable(tf.random.normal((32, 2), stddev=0.3))
        b2 = tf.Variable(tf.zeros(2))

        def step(x, y):
            with tf.GradientTape() as tape:
                h = tf.nn.conv2d(x, w1, 1, "SAME")
                h, _, _ = tf.compat.v1.nn.fused_batch_norm(
                    h, gamma, beta, is_training=True)
                h = tf.nn.relu(h)
                h = tf.nn.max_pool2d(h, 2, 2, "VALID")
                f = tf.reshape(h, (8, -1))[:, :32]
                logits = tf.nn.bias_add(tf.matmul(f, w2), b2)
                loss = tf.reduce_mean(
                    tf.nn.softmax_cross_entropy_with_logits(
                        labels=y, logits=logits))
            grads = tape.gradient(loss, [w1, gamma, beta, w2, b2])
            return [loss] + grads

        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        # STATIC batch: grad shape math (Shape→Prod/Range/Fill/
        # DynamicStitch chains) folds exactly
        conc = tf.function(step).get_concrete_function(
            tf.TensorSpec((8, 4, 4, 3), tf.float32),
            tf.TensorSpec((8, 2), tf.float32))
        frozen = convert_variables_to_constants_v2(conc)
        x = rng.normal(size=(8, 4, 4, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, size=8)]
        golden = [np.asarray(t) for t in frozen(tf.constant(x), tf.constant(y))]
        return frozen, x, y, golden

    def test_training_graph_loss_and_grads_match(self, rng):
        frozen, x, y, golden = self._build_step(rng)
        sd = import_graph_def(frozen.graph.as_graph_def())
        in_names = [i.name.split(":")[0] for i in frozen.inputs]
        keys = [sd.tf_name_map[o.name] for o in frozen.outputs]
        res = sd.output({in_names[0]: x, in_names[1]: y}, keys)
        for key, g in zip(keys, golden):
            np.testing.assert_allclose(np.asarray(res[key]), g,
                                       atol=2e-5, rtol=1e-4)

    def test_one_step_sgd_update_matches_tf(self, rng):
        frozen, x, y, golden = self._build_step(rng)
        sd = import_graph_def(frozen.graph.as_graph_def())
        in_names = [i.name.split(":")[0] for i in frozen.inputs]
        keys = [sd.tf_name_map[o.name] for o in frozen.outputs]
        res = sd.output({in_names[0]: x, in_names[1]: y}, keys)
        lr = 0.1
        # TF-side update from TF's own grads vs imported-graph update
        for key, g in zip(keys[1:], golden[1:]):
            ours = np.asarray(res[key])
            np.testing.assert_allclose(-lr * ours, -lr * g,
                                       atol=2e-6, rtol=1e-4)


def _onnx_attr_graph(name, graph_bytes):
    return pm.f_str(1, name) + pm.f_bytes(6, graph_bytes) + pm.f_varint(20, 5)


def _onnx_graph(nodes, initializers, inputs, outputs, name="sub"):
    g = b"".join(pm.f_bytes(1, n) for n in nodes)
    g += pm.f_str(2, name)
    g += b"".join(pm.f_bytes(5, i) for i in initializers)
    g += b"".join(pm.f_bytes(11, i) for i in inputs)
    g += b"".join(pm.f_bytes(12, pm.f_str(1, o)) for o in outputs)
    return g


class TestONNXScan:
    """ONNX Scan (VERDICT r3 missing #3 tail): no torch export emits Scan,
    so the graph is authored with protomini — body computes
    state' = state + elem; scan_out = 2*state' — and the import must
    lower to ONE lax.scan and match numpy."""

    def test_scan_state_and_outputs(self, rng):
        body = _onnx_graph(
            nodes=[
                _onnx_node("Add", ["st_in", "elem"], ["st_out"]),
                _onnx_node("Mul", ["st_out", "two"], ["scan_out"]),
            ],
            initializers=[_onnx_tensor("two", np.float32(2.0).reshape(()))],
            inputs=[_onnx_input("st_in", (4,)), _onnx_input("elem", (4,))],
            outputs=["st_out", "scan_out"],
        )
        model = _onnx_model(
            nodes=[_onnx_node("Scan", ["st0", "xs"], ["st_final", "ys"],
                              _onnx_attr_i("num_scan_inputs", 1),
                              _onnx_attr_graph("body", body))],
            initializers=[],
            inputs=[_onnx_input("st0", (4,)), _onnx_input("xs", (5, 4))],
            outputs=["st_final", "ys"],
        )
        st0 = rng.normal(size=(4,)).astype(np.float32)
        xs = rng.normal(size=(5, 4)).astype(np.float32)
        sd = import_onnx(model)
        res = sd.output({"st0": st0, "xs": xs}, ["st_final", "ys"])
        # numpy reference
        st = st0.copy()
        ys = []
        for t in range(5):
            st = st + xs[t]
            ys.append(2 * st)
        np.testing.assert_allclose(np.asarray(res["st_final"]), st,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(res["ys"]), np.stack(ys),
                                   atol=1e-6)

    def test_scan_rejects_reverse(self, rng):
        body = _onnx_graph(
            nodes=[_onnx_node("Identity", ["st_in"], ["st_out"])],
            initializers=[],
            inputs=[_onnx_input("st_in", (2,)), _onnx_input("elem", (2,))],
            outputs=["st_out"],
        )
        model = _onnx_model(
            nodes=[_onnx_node("Scan", ["st0", "xs"], ["st_final"],
                              _onnx_attr_i("num_scan_inputs", 1),
                              _onnx_attr_ints("scan_input_directions", [1]),
                              _onnx_attr_graph("body", body))],
            initializers=[],
            inputs=[_onnx_input("st0", (2,)), _onnx_input("xs", (3, 2))],
            outputs=["st_final"],
        )
        with pytest.raises(NotImplementedError, match="reverse"):
            import_onnx(model)


class TestONNXNestedControlFlow:
    """If nested inside a Loop body, with BOTH branches referencing
    enclosing-MODEL initializers by name (ONNX cross-scope capture) — the
    recursive capture collection in _external_refs/_subgraph_fn."""

    def test_if_inside_loop_with_outer_captures(self):
        then_g = _onnx_graph(
            nodes=[_onnx_node("Identity", ["one"], ["branch_out"])],
            initializers=[], inputs=[], outputs=["branch_out"], name="then")
        else_g = _onnx_graph(
            nodes=[_onnx_node("Identity", ["two"], ["branch_out"])],
            initializers=[], inputs=[], outputs=["branch_out"], name="else")
        body = _onnx_graph(
            nodes=[
                _onnx_node("Identity", ["cond_in"], ["cond_out"]),
                _onnx_node("Less", ["s_in", "thresh"], ["small"]),
                _onnx_node("If", ["small"], ["delta"],
                           _onnx_attr_graph("then_branch", then_g),
                           _onnx_attr_graph("else_branch", else_g)),
                _onnx_node("Add", ["s_in", "delta"], ["s_out"]),
            ],
            initializers=[],
            inputs=[_onnx_input("iter", ()), _onnx_input("cond_in", ()),
                    _onnx_input("s_in", ())],
            outputs=["cond_out", "s_out"], name="body")
        model = _onnx_model(
            nodes=[_onnx_node("Loop", ["M", "", "s0"], ["s_final"],
                              _onnx_attr_graph("body", body))],
            initializers=[
                _onnx_tensor("M", np.asarray(4, np.int64)),
                _onnx_tensor("one", np.float32(1.0).reshape(())),
                _onnx_tensor("two", np.float32(2.0).reshape(())),
                _onnx_tensor("thresh", np.float32(2.5).reshape(())),
            ],
            inputs=[_onnx_input("s0", ())],
            outputs=["s_final"])
        sd = import_onnx(model)
        out = np.asarray(sd.output({"s0": np.float32(0.0)}, ["s_final"])
                         ["s_final"])
        # 0 →+1→ 1 →+1→ 2 →+1→ 3 →+2→ 5  (s<2.5 adds one, else two)
        assert out == np.float32(5.0), out


class TestSparseSoftmaxCEImport:
    def test_sparse_ce_training_graph(self, rng):
        """tf.gradients graph using SPARSE (int-label) cross entropy — the
        other loss form real training exports use."""
        w = tf.Variable(tf.random.normal((6, 3), stddev=0.4, seed=5))

        def step(x, y):
            with tf.GradientTape() as tape:
                logits = tf.matmul(x, w)
                loss = tf.reduce_mean(
                    tf.nn.sparse_softmax_cross_entropy_with_logits(
                        labels=y, logits=logits))
            return [loss, tape.gradient(loss, w)]

        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )

        conc = tf.function(step).get_concrete_function(
            tf.TensorSpec((8, 6), tf.float32),
            tf.TensorSpec((8,), tf.int32))
        frozen = convert_variables_to_constants_v2(conc)
        x = rng.normal(size=(8, 6)).astype(np.float32)
        y = rng.integers(0, 3, size=8).astype(np.int32)
        golden = [np.asarray(t) for t in frozen(tf.constant(x),
                                                tf.constant(y))]
        sd = import_graph_def(frozen.graph.as_graph_def())
        in_names = [i.name.split(":")[0] for i in frozen.inputs]
        keys = [sd.tf_name_map[o.name] for o in frozen.outputs]
        res = sd.output({in_names[0]: x, in_names[1]: y}, keys)
        for key, g in zip(keys, golden):
            np.testing.assert_allclose(np.asarray(res[key]), g, atol=1e-5,
                                       rtol=1e-4)


class TestTFControlFlowSerialization:
    """Round-4: TF-imported control flow serializes too (__cf_while__/
    __cf_if__ structured nodes) — both the V2 functional ops and the V1
    dataflow-frame lowering."""

    @pytest.mark.parametrize("lower", [True, False],
                             ids=["v1-frames", "v2-functional"])
    def test_while_roundtrip(self, rng, lower, tmp_path):
        def fn(x):
            i = tf.constant(0)
            acc = x

            def cond(i, acc):
                return i < 4

            def body(i, acc):
                return i + 1, acc * 1.5 + 0.1

            i, acc = tf.while_loop(cond, body, [i, acc])
            return acc

        x = rng.normal(size=(2, 3)).astype(np.float32)
        gd, golden, in_names, out_names = _freeze_cf(fn, [x], lower)
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_names[0]]
        ref = np.asarray(sd.output({in_names[0]: x}, [key])[key])
        np.testing.assert_allclose(ref, golden[0], atol=1e-6)

        from deeplearning4j_tpu.samediff import SameDiff

        p = str(tmp_path / f"tfwhile{lower}.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = np.asarray(sd2.output({in_names[0]: x}, [key])[key])
        np.testing.assert_array_equal(out, ref)

    def test_functional_if_roundtrip(self, rng, tmp_path):
        def fn(x):
            return tf.cond(tf.reduce_sum(x) > 0,
                           lambda: x * 2.0, lambda: x - 1.0)

        x = rng.normal(size=(2, 3)).astype(np.float32) + 3.0
        gd, golden, in_names, out_names = _freeze_cf(fn, [x], lower=False)
        sd = import_graph_def(gd)
        key = sd.tf_name_map[out_names[0]]
        ref = np.asarray(sd.output({in_names[0]: x}, [key])[key])
        np.testing.assert_allclose(ref, golden[0], atol=1e-6)

        from deeplearning4j_tpu.samediff import SameDiff

        p = str(tmp_path / "tfif.sdz")
        sd.save(p)
        sd2 = SameDiff.load(p)
        out = np.asarray(sd2.output({in_names[0]: x}, [key])[key])
        np.testing.assert_array_equal(out, ref)


class TestTrainableImportedScan:
    def test_gradient_through_imported_scan_matches_analytic(self):
        """Captured constants stay RUNTIME inputs of control-flow nodes
        when the body builds without their static values — so an imported
        recurrent weight converted to a VARIABLE receives gradients
        (fine-tunable imported loops; lax.scan is reverse-differentiable)."""
        body = _onnx_graph(
            nodes=[_onnx_node("Add", ["st_in", "elem"], ["st_mid"]),
                   _onnx_node("Mul", ["st_mid", "w"], ["st_out"])],
            initializers=[],
            inputs=[_onnx_input("st_in", (4,)), _onnx_input("elem", (4,))],
            outputs=["st_out"])
        model = _onnx_model(
            nodes=[_onnx_node("Scan", ["st0", "xs"], ["st_final"],
                              _onnx_attr_i("num_scan_inputs", 1),
                              _onnx_attr_graph("body", body))],
            initializers=[_onnx_tensor("w", np.float32(0.9).reshape(()))],
            inputs=[_onnx_input("st0", (4,)), _onnx_input("xs", (5, 4))],
            outputs=["st_final"])
        sd = import_onnx(model)
        sd.convert_to_variable("w")
        loss = sd._op("sum", [sd.get_variable("st_final")])
        sd.set_loss_variables(loss)
        grads = sd.calculate_gradients(
            {"st0": np.zeros(4, np.float32),
             "xs": np.ones((5, 4), np.float32)}, "w")
        dw = float(np.asarray(grads["w"]))
        w, st, d = 0.9, np.zeros(4), np.zeros(4)
        for _ in range(5):
            d = (st + 1.0) + w * d
            st = (st + 1.0) * w
        np.testing.assert_allclose(dw, 4 * d[0], rtol=1e-5)


class TestONNXReverseSequence:
    def test_reverse_sequence_matches_numpy(self, rng):
        model = _onnx_model(
            nodes=[_onnx_node("ReverseSequence", ["x", "lens"], ["y"],
                              _onnx_attr_i("time_axis", 1),
                              _onnx_attr_i("batch_axis", 0))],
            initializers=[_onnx_tensor("lens",
                                       np.asarray([3, 1, 4], np.int64))],
            inputs=[_onnx_input("x", (3, 4))],
            outputs=["y"])
        x = rng.normal(size=(3, 4)).astype(np.float32)
        sd = import_onnx(model)
        out = np.asarray(sd.output({"x": x}, ["y"])["y"])
        ref = x.copy()
        for b, n in enumerate([3, 1, 4]):
            ref[b, :n] = x[b, :n][::-1]
        np.testing.assert_allclose(out, ref, atol=1e-6)
