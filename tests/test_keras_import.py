"""Keras HDF5 import — per-model golden outputs vs tf.keras.

Reference test parity: deeplearning4j-modelimport tests (full-model import
vs Keras-saved activations; SURVEY.md §4)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.imports import KerasModelImport  # noqa: E402


def _roundtrip(model, x, tmp_path, atol=1e-5):
    path = str(tmp_path / "model.h5")
    model.save(path)
    golden = np.asarray(model(x))
    net = KerasModelImport.import_keras_model_and_weights(path)
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, golden, atol=atol, rtol=1e-4)
    return net


def test_mlp(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(4, activation="softmax"),
    ])
    x = rng.normal(size=(5, 6)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_cnn_bn_pool(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((12, 12, 3)),
        tf.keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
        tf.keras.layers.BatchNormalization(),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(4, 3, padding="valid"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(5, activation="softmax"),
    ])
    # non-trivial BN stats: run a training step so moving stats move
    m.compile("sgd", "categorical_crossentropy")
    xs = rng.normal(size=(16, 12, 12, 3)).astype(np.float32)
    ys = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 16)]
    m.fit(xs, ys, epochs=1, verbose=0)
    x = rng.normal(size=(2, 12, 12, 3)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-4)


def test_lstm(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.LSTM(6, return_sequences=True),
    ])
    x = rng.normal(size=(3, 7, 5)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-5)


def test_gru(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.GRU(6, return_sequences=True),
    ])
    x = rng.normal(size=(3, 7, 5)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-5)


def test_gru_nondefault_recurrent_activation(rng, tmp_path):
    # regression: recurrent_activation must map to gate_activation, not be
    # silently dropped (→ sigmoid gates, wrong numerics)
    m = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.GRU(6, recurrent_activation="tanh",
                            return_sequences=True),
    ])
    x = rng.normal(size=(3, 7, 5)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-5)


def test_embedding_pooling(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((9,)),
        tf.keras.layers.Embedding(20, 8),
        tf.keras.layers.GlobalAveragePooling1D(),
        tf.keras.layers.Dense(3, activation="softmax"),
    ])
    x = rng.integers(0, 20, size=(4, 9)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_unsupported_layer_reports_name(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((4, 4, 1)),
        tf.keras.layers.ConvLSTM1D(2, 2),
    ])
    path = str(tmp_path / "m.h5")
    m.save(path)
    with pytest.raises(ValueError, match="ConvLSTM1D"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_functional_dag_import(rng, tmp_path):
    inp = tf.keras.Input((8,), name="in0")
    a = tf.keras.layers.Dense(4, activation="relu", name="d1")(inp)
    b = tf.keras.layers.Dense(4, activation="relu", name="d2")(inp)
    m = tf.keras.layers.Add(name="add")([a, b])
    c = tf.keras.layers.Concatenate(name="cat")([m, a])
    out = tf.keras.layers.Dense(3, activation="softmax", name="out")(c)
    model = tf.keras.Model(inp, out)
    path = str(tmp_path / "f.h5")
    model.save(path)
    x = rng.normal(size=(5, 8)).astype(np.float32)
    golden = np.asarray(model(x))
    net = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, golden, atol=1e-5, rtol=1e-4)


def test_functional_cnn_residual_import(rng, tmp_path):
    inp = tf.keras.Input((8, 8, 3), name="img")
    h = tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu",
                               name="c1")(inp)
    r = tf.keras.layers.Conv2D(4, 3, padding="same", name="c2")(h)
    s = tf.keras.layers.Add(name="res")([h, r])
    g = tf.keras.layers.GlobalAveragePooling2D(name="gap")(s)
    out = tf.keras.layers.Dense(2, activation="softmax", name="head")(g)
    model = tf.keras.Model(inp, out)
    path = str(tmp_path / "r.h5")
    model.save(path)
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    golden = np.asarray(model(x))
    net = KerasModelImport.import_keras_model_and_weights(path)
    np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                               atol=1e-4, rtol=1e-4)


def test_functional_fanout_two_heads(rng, tmp_path):
    # fan-out without a merge: must route through ComputationGraph, not the
    # sequential path (regression: chain heuristic misclassified this)
    inp = tf.keras.Input((6,), name="x")
    h = tf.keras.layers.Dense(5, activation="relu", name="trunk")(inp)
    o1 = tf.keras.layers.Dense(5, activation="softmax", name="o1")(h)
    o2 = tf.keras.layers.Dense(5, activation="softmax", name="o2")(h)
    model = tf.keras.Model(inp, [o1, o2])
    path = str(tmp_path / "two.h5")
    model.save(path)
    x = rng.normal(size=(3, 6)).astype(np.float32)
    g1, g2 = [np.asarray(t) for t in model(x)]
    net = KerasModelImport.import_keras_model_and_weights(path)
    p1, p2 = [np.asarray(p) for p in net.output(x)]
    np.testing.assert_allclose(p1, g1, atol=1e-5)
    np.testing.assert_allclose(p2, g2, atol=1e-5)


def test_functional_flatten_head(rng, tmp_path):
    inp = tf.keras.Input((6, 6, 2), name="img")
    h = tf.keras.layers.Conv2D(3, 3, padding="same", activation="relu",
                               name="c")(inp)
    s = tf.keras.layers.Add(name="skip")([h, h])
    f = tf.keras.layers.Flatten(name="flat")(s)
    out = tf.keras.layers.Dense(4, activation="softmax", name="head")(f)
    model = tf.keras.Model(inp, out)
    path = str(tmp_path / "fl.h5")
    model.save(path)
    x = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
    golden = np.asarray(model(x))
    net = KerasModelImport.import_keras_model_and_weights(path)
    np.testing.assert_allclose(np.asarray(net.output(x)), golden,
                               atol=1e-4, rtol=1e-4)


def test_functional_weight_sharing(rng, tmp_path):
    """A layer called twice imports as one param set + a SharedLayer node
    (KerasModel.java models this as repeated layers over one weight set)."""
    inp1 = tf.keras.Input((4,), name="a")
    inp2 = tf.keras.Input((4,), name="b")
    shared = tf.keras.layers.Dense(3, name="shared", activation="tanh")
    m = tf.keras.layers.Concatenate(name="cat")([shared(inp1), shared(inp2)])
    model = tf.keras.Model([inp1, inp2], tf.keras.layers.Dense(2, name="o")(m))
    path = str(tmp_path / "sh.h5")
    model.save(path)
    x1 = rng.normal(size=(5, 4)).astype(np.float32)
    x2 = rng.normal(size=(5, 4)).astype(np.float32)
    golden = np.asarray(model([x1, x2]))
    net = KerasModelImport.import_keras_model_and_weights(path)
    got = np.asarray(net.output(x1, x2))
    np.testing.assert_allclose(got, golden, atol=1e-5, rtol=1e-4)
    # exactly ONE param set for the shared layer
    assert "shared" in net.params and net.params["shared"]
    assert not net.params.get("shared@1")
    # gradients from BOTH call sites accumulate into the source when training
    from deeplearning4j_tpu.nn.transfer import TransferLearning
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd

    trainable = (TransferLearning.GraphBuilder(net)
                 .remove_vertex_and_connections("o")
                 .add_layer("head", OutputLayer(n_in=6, n_out=2), "cat")
                 .set_outputs("head")
                 .build())
    w_before = np.asarray(trainable.params["shared"]["W"]).copy()
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 5)]
    trainable.fit([x1, x2], [ys], epochs=3)
    assert not np.allclose(np.asarray(trainable.params["shared"]["W"]),
                           w_before)


def test_functional_shared_embedding_siamese(rng, tmp_path):
    """Siamese-style shared embedding over two inputs (the classic
    weight-sharing shape)."""
    inp1 = tf.keras.Input((6,), name="l")
    inp2 = tf.keras.Input((6,), name="r")
    tower = tf.keras.layers.Dense(5, activation="relu", name="tower")
    d = tf.keras.layers.Subtract(name="diff")([tower(inp1), tower(inp2)])
    out = tf.keras.layers.Dense(1, activation="sigmoid", name="score")(d)
    model = tf.keras.Model([inp1, inp2], out)
    path = str(tmp_path / "siam.h5")
    model.save(path)
    x1 = rng.normal(size=(3, 6)).astype(np.float32)
    x2 = rng.normal(size=(3, 6)).astype(np.float32)
    golden = np.asarray(model([x1, x2]))
    net = KerasModelImport.import_keras_model_and_weights(path)
    np.testing.assert_allclose(np.asarray(net.output(x1, x2)), golden,
                               atol=1e-5, rtol=1e-4)


# -- round-2 breadth builders (VERDICT r1 missing #6) ------------------------


def test_bidirectional_lstm(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.LSTM(6, return_sequences=True)),
    ])
    x = rng.normal(size=(3, 7, 5)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-5)


def test_bidirectional_gru_sum_mode(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((6, 4)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.GRU(5, return_sequences=True), merge_mode="sum"),
    ])
    x = rng.normal(size=(2, 6, 4)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-5)


def test_depthwise_conv2d(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((8, 8, 3)),
        tf.keras.layers.DepthwiseConv2D(3, depth_multiplier=2,
                                        padding="same", activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
    ])
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_conv1d_pool1d_stack(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((16, 4)),
        tf.keras.layers.Conv1D(8, 3, padding="same", activation="relu"),
        tf.keras.layers.MaxPooling1D(2),
        tf.keras.layers.Conv1D(6, 3, padding="valid"),
        tf.keras.layers.AveragePooling1D(2),
        tf.keras.layers.GlobalMaxPooling1D(),
        tf.keras.layers.Dense(3, activation="softmax"),
    ])
    x = rng.normal(size=(4, 16, 4)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_conv3d_pool3d(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((6, 6, 6, 2)),
        tf.keras.layers.Conv3D(4, 2, padding="valid", activation="relu"),
        tf.keras.layers.MaxPooling3D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(3),
    ])
    x = rng.normal(size=(2, 6, 6, 6, 2)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-4)


def test_repeat_vector_time_distributed(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((5,)),
        tf.keras.layers.Dense(6, activation="tanh"),
        tf.keras.layers.RepeatVector(4),
        tf.keras.layers.TimeDistributed(
            tf.keras.layers.Dense(3, activation="relu")),
    ])
    x = rng.normal(size=(3, 5)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_padding_cropping_upsampling_1d(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((10, 3)),
        tf.keras.layers.ZeroPadding1D(2),
        tf.keras.layers.Cropping1D((1, 2)),
        tf.keras.layers.UpSampling1D(2),
    ])
    x = rng.normal(size=(2, 10, 3)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_prelu(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((8, 8, 3)),
        tf.keras.layers.Conv2D(4, 3, padding="same"),
        tf.keras.layers.PReLU(shared_axes=[1, 2]),
        tf.keras.layers.GlobalAveragePooling2D(),
    ])
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def test_conv_lstm2d(rng, tmp_path):
    """ConvLSTM2D (VERDICT r2 missing #6): keras [i,f,c,o] conv-gate kernels
    permute onto the hoisted-input-conv scan."""
    m = tf.keras.Sequential([
        tf.keras.layers.Input((4, 6, 6, 2)),
        tf.keras.layers.ConvLSTM2D(3, (3, 3), padding="same",
                                   return_sequences=True),
    ])
    x = rng.normal(size=(2, 4, 6, 6, 2)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-4)


def test_conv_lstm2d_last_state(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((3, 5, 5, 2)),
        tf.keras.layers.ConvLSTM2D(4, (3, 3), padding="valid",
                                   strides=(2, 2), return_sequences=False),
    ])
    x = rng.normal(size=(2, 3, 5, 5, 2)).astype(np.float32)
    _roundtrip(m, x, tmp_path, atol=1e-4)


def test_masking_lstm(rng, tmp_path):
    """Masking semantics (VERDICT r2 missing #6): zero-padded timesteps are
    skipped by the downstream LSTM exactly as keras does."""
    m = tf.keras.Sequential([
        tf.keras.layers.Input((8, 3)),
        tf.keras.layers.Masking(mask_value=0.0),
        tf.keras.layers.LSTM(5, return_sequences=True),
    ])
    x = rng.normal(size=(4, 8, 3)).astype(np.float32)
    x[:, 5:] = 0.0  # padded tail
    x[1, 2] = 0.0   # masked step mid-sequence
    _roundtrip(m, x, tmp_path, atol=1e-5)


def test_masking_convlstm2d(rng, tmp_path):
    """Masking on 5-D image sequences (mask derived over all feature axes)."""
    m = tf.keras.Sequential([
        tf.keras.layers.Input((4, 5, 5, 2)),
        tf.keras.layers.Masking(mask_value=0.0),
        tf.keras.layers.ConvLSTM2D(3, (3, 3), padding="same",
                                   return_sequences=True),
    ])
    x = rng.normal(size=(2, 4, 5, 5, 2)).astype(np.float32)
    x[:, 2:] = 0.0
    _roundtrip(m, x, tmp_path, atol=1e-4)


def test_masking_then_dense_rejected(rng, tmp_path):
    """Masking before a non-mask-consuming layer diverges from Keras (Keras
    computes Dense at every step) — must reject, not silently forward-fill."""
    from deeplearning4j_tpu.imports.keras_import import KerasImportError

    m = tf.keras.Sequential([
        tf.keras.layers.Input((6, 3)),
        tf.keras.layers.Masking(mask_value=0.0),
        tf.keras.layers.Dense(4),
    ])
    path = str(tmp_path / "m.h5")
    m.save(path)
    with pytest.raises(KerasImportError, match="Masking"):
        KerasModelImport.import_keras_model_and_weights(path)


def test_leaky_relu_and_noise_layers(rng, tmp_path):
    """LeakyReLU keeps keras's alpha (0.3 default, not the op's 0.01);
    Gaussian noise/dropout are identity at inference."""
    m = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(5),
        tf.keras.layers.LeakyReLU(),
        tf.keras.layers.GaussianNoise(0.5),
        tf.keras.layers.Dense(3),
        tf.keras.layers.GaussianDropout(0.3),
    ])
    x = rng.normal(size=(4, 6)).astype(np.float32)
    _roundtrip(m, x, tmp_path)


def _roundtrip_v3(model, x, tmp_path, atol=1e-5):
    """Same as _roundtrip but through the Keras v3 .keras zip format."""
    path = str(tmp_path / "model.keras")
    model.save(path)
    golden = np.asarray(model(x))
    net = KerasModelImport.import_keras_model_and_weights(path)
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, golden, atol=atol, rtol=1e-4)
    return net


def test_keras_v3_format_mlp(rng, tmp_path):
    """Keras v3 .keras zip (the modern default save format — beyond the
    reference's HDF5-only importer)."""
    m = tf.keras.Sequential([
        tf.keras.layers.Input((6,)),
        tf.keras.layers.Dense(16, activation="relu"),
        tf.keras.layers.Dense(4, activation="softmax"),
    ])
    x = rng.normal(size=(5, 6)).astype(np.float32)
    _roundtrip_v3(m, x, tmp_path)


def test_keras_v3_format_cnn_bn(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((10, 10, 2)),
        tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
        tf.keras.layers.BatchNormalization(),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(3),
    ])
    m.compile("sgd", "mse")
    xs = rng.normal(size=(8, 10, 10, 2)).astype(np.float32)
    m.fit(xs, rng.normal(size=(8, 3)).astype(np.float32), epochs=1, verbose=0)
    x = rng.normal(size=(2, 10, 10, 2)).astype(np.float32)
    _roundtrip_v3(m, x, tmp_path, atol=1e-4)


def test_keras_v3_format_lstm(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((7, 5)),
        tf.keras.layers.LSTM(6, return_sequences=True),
    ])
    x = rng.normal(size=(3, 7, 5)).astype(np.float32)
    _roundtrip_v3(m, x, tmp_path)


def test_keras_v3_format_bidirectional(rng, tmp_path):
    m = tf.keras.Sequential([
        tf.keras.layers.Input((6, 4)),
        tf.keras.layers.Bidirectional(
            tf.keras.layers.LSTM(3, return_sequences=True)),
    ])
    x = rng.normal(size=(2, 6, 4)).astype(np.float32)
    _roundtrip_v3(m, x, tmp_path)


class TestKerasFullModelCorpus:
    """Architecture-scale corpus for the KERAS importer (J13) — the
    keras-side analogue of tests/test_import_corpus.py: full
    keras.applications functional graphs saved as .keras v3 zips, imported
    into ComputationGraphs, golden-matched against keras itself. Covers
    residual adds (ResNet50), inverted residuals + BN6 (MobileNetV2),
    depthwise-separable towers (Xception), dense concat blocks
    (DenseNet121)."""

    RES = 64

    def _builders(self):
        return {
            "ResNet50": lambda: tf.keras.applications.ResNet50(
                weights=None, include_top=False,
                input_shape=(self.RES, self.RES, 3), pooling="avg"),
            "MobileNetV2": lambda: tf.keras.applications.MobileNetV2(
                weights=None, include_top=False,
                input_shape=(self.RES, self.RES, 3), pooling="avg"),
            "Xception": lambda: tf.keras.applications.Xception(
                weights=None, include_top=False, input_shape=(96, 96, 3),
                pooling="avg"),
            "DenseNet121": lambda: tf.keras.applications.DenseNet121(
                weights=None, include_top=False,
                input_shape=(self.RES, self.RES, 3), pooling="avg"),
        }

    @pytest.mark.parametrize("name", ["ResNet50", "MobileNetV2", "Xception",
                                      "DenseNet121"])
    def test_applications_golden(self, name, tmp_path, rng):
        tf.keras.utils.set_random_seed(7)
        model = self._builders()[name]()
        path = str(tmp_path / f"{name}.keras")
        model.save(path)
        shp = model.input_shape[1:]
        x = rng.normal(size=(2,) + shp).astype(np.float32)
        golden = model(x, training=False).numpy()
        net = KerasModelImport.import_keras_model_and_weights(path)
        out = np.asarray(net.output(x))
        np.testing.assert_allclose(out, golden, atol=1e-4, rtol=1e-4)


def test_conv2d_transpose(rng, tmp_path):
    """Round-5: Conv2DTranspose -> Deconvolution2D (kernel flip+swap
    verified against an fp64 manual conv-transpose)."""
    tf.keras.utils.set_random_seed(11)
    model = tf.keras.Sequential([
        tf.keras.Input((5, 5, 3)),
        tf.keras.layers.Conv2DTranspose(4, (3, 3), strides=(2, 2),
                                        padding="same",
                                        activation="relu"),
        tf.keras.layers.Conv2DTranspose(2, (3, 3), padding="valid"),
    ])
    x = rng.normal(size=(2, 5, 5, 3)).astype(np.float32)
    _roundtrip(model, x, tmp_path, atol=1e-4)


def test_conv2d_transpose_no_bias_valid(rng, tmp_path):
    tf.keras.utils.set_random_seed(12)
    model = tf.keras.Sequential([
        tf.keras.Input((6, 6, 2)),
        tf.keras.layers.Conv2DTranspose(3, (2, 2), strides=(3, 3),
                                        padding="valid", use_bias=False),
    ])
    x = rng.normal(size=(1, 6, 6, 2)).astype(np.float32)
    _roundtrip(model, x, tmp_path, atol=1e-4)


def test_global_pooling_3d(rng, tmp_path):
    tf.keras.utils.set_random_seed(5)
    model = tf.keras.Sequential([
        tf.keras.Input((4, 4, 4, 2)),
        tf.keras.layers.Conv3D(3, (2, 2, 2), padding="same"),
        tf.keras.layers.GlobalAveragePooling3D(),
    ])
    x = rng.normal(size=(2, 4, 4, 4, 2)).astype(np.float32)
    _roundtrip(model, x, tmp_path, atol=1e-5)


def test_global_pooling_guards(rng, tmp_path):
    """channels_first / keepdims configs must fail LOUDLY, not mis-pool."""
    from deeplearning4j_tpu.imports.keras_import import KerasImportError

    tf.keras.utils.set_random_seed(6)
    model = tf.keras.Sequential([
        tf.keras.Input((4, 4, 2)),
        tf.keras.layers.GlobalAveragePooling2D(keepdims=True),
    ])
    path = str(tmp_path / "kd.h5")
    model.save(path)
    with pytest.raises(KerasImportError, match="keepdims"):
        KerasModelImport.import_keras_model_and_weights(path)
