"""Worker process for the multi-process DCN-bootstrap test.

Usage: python _dist_worker.py <coordinator> <num_processes> <process_id>

Each process runs the SAME SPMD program over the GLOBAL mesh (the TPU-native
shape of SharedTrainingMaster workers — SURVEY.md §3.4): the gradient
all-reduce is emitted by the partitioner and rides the cross-process
collective channel the coordinator bootstrapped."""

import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.parallel import distributed  # noqa: E402


def main():
    coordinator, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    distributed.initialize(coordinator=coordinator, num_processes=nproc,
                           process_id=pid)
    assert distributed.process_count() == nproc
    assert distributed.process_index() == pid
    assert distributed.is_coordinator() == (pid == 0)

    tmesh = distributed.global_mesh()
    mesh = tmesh.mesh
    n_dev = len(jax.devices())

    D = 8
    rng = np.random.default_rng(0)  # same data recipe on every process
    w_true = rng.normal(size=(D,)).astype(np.float32)
    # deterministic global batch; each process materializes its local rows
    B = 4 * n_dev
    X = rng.normal(size=(B, D)).astype(np.float32)
    Y = X @ w_true

    xsh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    n_local = B // nproc
    lo = pid * n_local
    x = jax.make_array_from_process_local_data(xsh, X[lo: lo + n_local])
    y = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), Y[lo: lo + n_local])
    w = jax.make_array_from_process_local_data(
        rep, np.zeros((D,), np.float32))

    @jax.jit
    def step(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)

        g = jax.grad(loss)(w)  # partitioner inserts the cross-host allreduce
        return w - 0.2 * g

    for _ in range(30):
        w = step(w, x, y)
    w_final = np.asarray(jax.device_get(w))
    print(json.dumps({
        "pid": pid,
        "n_devices_global": n_dev,
        "w": [round(float(v), 6) for v in w_final],
        "err": round(float(np.abs(w_final - w_true).max()), 6),
    }), flush=True)
    distributed.shutdown()


if __name__ == "__main__":
    main()
