"""Worker process for the multi-process DCN-bootstrap and elastic tests.

Usage:
    python _dist_worker.py <coordinator> <num_processes> <process_id>
        [--local-dp]
    python _dist_worker.py --elastic <shared_dir> <process_id> <world>
        [sigkill_at_step]

``--elastic`` runs the supervised elastic runtime (parallel/elastic.py):
membership over a shared directory (NOT jax.distributed — a SIGKILLed peer
must not take the PJRT control plane down with it; the data plane per
process is local DP, the r7 CPU-backend stance), checkpoint-auto-resume,
epoch-boundary regroup. With ``sigkill_at_step`` the process arms the
``sigkill_host`` fault against itself — the surviving process must notice
the missed heartbeats, regroup to a smaller world, re-shard the batches,
and finish."""

import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.parallel import distributed  # noqa: E402


def _local_dp(nproc, pid):
    """``--local-dp`` mode (the __graft_entry__ DCN dryrun): prove the
    CONTROL plane — gRPC coordinator bootstrap, global device view, process
    roles — then run the DP step on this process's own addressable devices.
    The cross-process data plane is probed but allowed to be unavailable:
    this jaxlib's CPU backend rejects multiprocess computations ("Multiprocess
    computations aren't implemented on the CPU backend"), a backend ceiling,
    not a bootstrap defect — on TPU the same program spans the global mesh."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n_global, n_local = len(jax.devices()), len(jax.local_devices())
    mesh = Mesh(np.array(jax.local_devices()), ("data",))

    D = 8
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(D,)).astype(np.float32)
    B = 4 * n_local
    X = rng.normal(size=(B, D)).astype(np.float32)
    Y = X @ w_true
    x = jax.device_put(X, NamedSharding(mesh, P("data")))
    y = jax.device_put(Y, NamedSharding(mesh, P("data")))
    w = jax.device_put(np.zeros((D,), np.float32), NamedSharding(mesh, P()))

    @jax.jit
    def step(w, x, y):
        g = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
        return w - 0.2 * g

    for _ in range(30):
        w = step(w, x, y)
    err = float(np.abs(np.asarray(jax.device_get(w)) - w_true).max())

    # opportunistic global-step probe: works on real multi-host backends,
    # expected to be rejected by the CPU backend
    try:
        gmesh = distributed.global_mesh().mesh
        xg = jax.make_array_from_process_local_data(
            NamedSharding(gmesh, P("data")), X[: B // nproc])
        jax.jit(lambda a: a * 2.0)(xg).block_until_ready()
        global_step = "ok"
    except Exception as e:  # noqa: BLE001
        global_step = f"unavailable ({type(e).__name__})"

    print(json.dumps({
        "pid": pid,
        "coordinator": distributed.is_coordinator(),
        "n_devices_global": n_global,
        "n_devices_local": n_local,
        "local_dp_err": round(err, 6),
        "global_step": global_step,
    }), flush=True)
    distributed.shutdown()


def _elastic(shared_dir, pid, world, sigkill_at=None):
    """``--elastic`` mode: one member of a supervised elastic pod."""
    import os

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel import ElasticTrainer, FileMembership
    from deeplearning4j_tpu.util.faults import SIGKILL_HOST, get_injector

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=4, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)  # same data recipe on every member
    xs = rng.standard_normal((64, 8)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    it = ArrayDataSetIterator(xs, ys, batch=8)  # 8 batches / epoch

    if sigkill_at is not None:
        get_injector().inject(SIGKILL_HOST, at_step=sigkill_at)
    membership = FileMembership(
        os.path.join(shared_dir, "membership"), process_id=pid,
        world_size=world, heartbeat_interval=0.3, miss_threshold=8,
        barrier_timeout=90.0, log_fn=None)
    trainer = ElasticTrainer(
        net, os.path.join(shared_dir, f"ckpt-{pid}"), checkpoint_every=4,
        membership=membership, log_fn=None)
    trainer.fit(it, epochs=3)
    view = membership.view
    print(json.dumps({
        "pid": pid,
        "state": trainer.state,
        "iteration": net.iteration,
        "epoch": net.epoch,
        "world_final": view.world if view else None,
        "members_final": list(view.members) if view else None,
        "regroups": membership.regroups,
        "score_finite": bool(np.isfinite(float(net.score_value))),
    }), flush=True)


def _elastic_compress(shared_dir, pid, world, sigkill_at=None):
    """``--elastic-compress`` mode: one member of a supervised elastic pod
    whose data plane is the COMPRESSED ParallelWrapper DP step
    (parallel/compression.py). Proves the residual/threshold state rides
    the elastic machinery: a SIGKILLed peer's loss regroups the survivor
    (whose wrapper re-shards with its residual migrated in place), and the
    final checkpoint carries the residual EXACTLY (bit-compared against a
    fresh restore before reporting)."""
    import os

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel import (ElasticTrainer, FileMembership,
                                             ParallelWrapper, TrainingMesh)
    from deeplearning4j_tpu.util.faults import SIGKILL_HOST, get_injector

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.05))
                .grad_compression("threshold", threshold=1e-3)
                .list()
                .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=4, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    net = build_net()
    pw = ParallelWrapper(net, mesh=TrainingMesh(data=len(jax.devices())),
                         replicas=4, skew_every=0)
    rng = np.random.default_rng(0)  # same data recipe on every member
    xs = rng.standard_normal((64, 8)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    it = ArrayDataSetIterator(xs, ys, batch=8)  # 8 batches / epoch

    if sigkill_at is not None:
        get_injector().inject(SIGKILL_HOST, at_step=sigkill_at)
    membership = FileMembership(
        os.path.join(shared_dir, "membership"), process_id=pid,
        world_size=world, heartbeat_interval=0.3, miss_threshold=8,
        barrier_timeout=90.0, log_fn=None)
    trainer = ElasticTrainer(
        pw, os.path.join(shared_dir, f"ckpt-{pid}"), checkpoint_every=4,
        membership=membership, log_fn=None)
    trainer.fit(it, epochs=3)

    # checkpoint-resume carries the residual exactly: restore the FINAL
    # checkpoint into a fresh net and bit-compare the compression state
    net2 = build_net()
    trainer.ckpt.restore(net2)
    live = jax.tree_util.tree_leaves(net._grad_comp_state)
    restored = jax.tree_util.tree_leaves(net2._grad_comp_state)
    residual_exact = (
        len(live) == len(restored)
        and all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(live, restored))
        and any(np.asarray(a).any() for a in live))  # non-trivial residual

    view = membership.view
    stats = pw.compression_stats()
    print(json.dumps({
        "pid": pid,
        "state": trainer.state,
        "iteration": net.iteration,
        "epoch": net.epoch,
        "world_final": view.world if view else None,
        "members_final": list(view.members) if view else None,
        "regroups": membership.regroups,
        "score_finite": bool(np.isfinite(float(net.score_value))),
        "residual_exact": bool(residual_exact),
        "wire_bytes": stats["wire_bytes"] if stats else None,
        "threshold": stats["threshold"] if stats else None,
    }), flush=True)


def _pipe(shared_dir, pid, world, sigkill_at=None):
    """``--pipe`` mode: one member of a supervised elastic pod whose data
    plane is the PIPELINED trainer (parallel/pipelined.py) — stacked stage
    params/optimizer state, GPipe microbatch schedule, lane-decomposed DP.
    Proves the stacked stage state rides the elastic machinery: a
    SIGKILLed peer's loss regroups the survivor (reshard() syncs the
    stacked state through model layout and re-places it), and the final
    checkpoint restores BIT-exactly at the boundary (the restored net's
    re-stacked pipeline state is bit-compared in-process against the live
    trainer's)."""
    import os

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel import (ElasticTrainer, FileMembership,
                                             PipelinedTrainer, TrainingMesh)
    from deeplearning4j_tpu.util.faults import SIGKILL_HOST, get_injector

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
                .pipe_stages(2).n_micro(2)
                .list()
                .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
                .stage_boundary()
                .layer(DenseLayer(n_in=16, n_out=16, activation="tanh"))
                .stage_boundary()
                .layer(DenseLayer(n_in=16, n_out=16, activation="tanh"))
                .stage_boundary()
                .layer(OutputLayer(n_in=16, n_out=4, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    def build_trainer(net):
        return PipelinedTrainer(
            net, mesh=TrainingMesh(data=len(jax.devices())),
            replicas=2, skew_every=0)

    net = build_net()
    pt = build_trainer(net)
    rng = np.random.default_rng(0)  # same data recipe on every member
    xs = rng.standard_normal((64, 8)).astype(np.float32)
    ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 64)]
    it = ArrayDataSetIterator(xs, ys, batch=8)  # 8 batches / epoch

    if sigkill_at is not None:
        get_injector().inject(SIGKILL_HOST, at_step=sigkill_at)
    membership = FileMembership(
        os.path.join(shared_dir, "membership"), process_id=pid,
        world_size=world, heartbeat_interval=0.3, miss_threshold=8,
        barrier_timeout=90.0, log_fn=None)
    trainer = ElasticTrainer(
        pt, os.path.join(shared_dir, f"ckpt-{pid}"), checkpoint_every=4,
        membership=membership, log_fn=None)
    trainer.fit(it, epochs=3)

    # the final (blocking, synced) checkpoint must restore the STACKED
    # stage state bit-exactly: restore into a fresh net, re-stack through
    # a fresh trainer, and compare every placed leaf
    net2 = build_net()
    trainer.ckpt.restore(net2)
    pt2 = build_trainer(net2)
    pt2._build()
    pt.sync_model()  # no-op value-wise (fit already synced at checkpoint)
    live = jax.tree_util.tree_leaves(
        {"params": pt._pp["params"], "opts": pt._pp["opts"]})
    restored = jax.tree_util.tree_leaves(
        {"params": pt2._pp["params"], "opts": pt2._pp["opts"]})
    stacked_exact = (
        len(live) == len(restored)
        and all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(live, restored)))

    view = membership.view
    print(json.dumps({
        "pid": pid,
        "state": trainer.state,
        "iteration": net.iteration,
        "epoch": net.epoch,
        "world_final": view.world if view else None,
        "members_final": list(view.members) if view else None,
        "regroups": membership.regroups,
        "score_finite": bool(np.isfinite(float(net.score_value))),
        "stacked_exact": bool(stacked_exact),
        "pipe_stages": pt.pipe_stages,
        "bubble_fraction": pt.bubble_fraction,
    }), flush=True)


def main():
    if sys.argv[1] == "--pipe":
        _pipe(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
              int(sys.argv[5]) if len(sys.argv) > 5 else None)
        return
    if sys.argv[1] == "--elastic":
        _elastic(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
                 int(sys.argv[5]) if len(sys.argv) > 5 else None)
        return
    if sys.argv[1] == "--elastic-compress":
        _elastic_compress(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
                          int(sys.argv[5]) if len(sys.argv) > 5 else None)
        return
    coordinator, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    distributed.initialize(coordinator=coordinator, num_processes=nproc,
                           process_id=pid)
    assert distributed.process_count() == nproc
    assert distributed.process_index() == pid
    assert distributed.is_coordinator() == (pid == 0)

    if len(sys.argv) > 4 and sys.argv[4] == "--local-dp":
        _local_dp(nproc, pid)
        return

    tmesh = distributed.global_mesh()
    mesh = tmesh.mesh
    n_dev = len(jax.devices())

    D = 8
    rng = np.random.default_rng(0)  # same data recipe on every process
    w_true = rng.normal(size=(D,)).astype(np.float32)
    # deterministic global batch; each process materializes its local rows
    B = 4 * n_dev
    X = rng.normal(size=(B, D)).astype(np.float32)
    Y = X @ w_true

    xsh = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    n_local = B // nproc
    lo = pid * n_local

    @jax.jit
    def step(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)

        g = jax.grad(loss)(w)  # partitioner inserts the cross-host allreduce
        return w - 0.2 * g

    data_plane = "global"
    try:
        x = jax.make_array_from_process_local_data(xsh, X[lo: lo + n_local])
        y = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")), Y[lo: lo + n_local])
        w = jax.make_array_from_process_local_data(
            rep, np.zeros((D,), np.float32))
        for _ in range(30):
            w = step(w, x, y)
        w_final = np.asarray(jax.device_get(w))
    except Exception as e:  # noqa: BLE001
        # This jaxlib's CPU backend rejects cross-process computations
        # ("Multiprocess computations aren't implemented on the CPU
        # backend") — a backend ceiling, not a bootstrap defect (the r7
        # DCN-dryrun stance). Fall back LOUDLY: every process runs the
        # SAME deterministic global-batch DP step on its local 2-device
        # mesh, so the cross-process identity assertion still has teeth
        # (identical programs on identical data must agree bit-for-bit)
        # while the global device view proves the control plane. On real
        # ICI/DCN hardware the try-branch is the path that runs.
        if "Multiprocess computations" not in repr(e):
            raise
        data_plane = f"local_fallback({type(e).__name__}: cpu backend)"
        from jax.sharding import Mesh

        lmesh = Mesh(np.array(jax.local_devices()), ("data",))
        lsh = NamedSharding(lmesh, P("data"))
        lrep = NamedSharding(lmesh, P())
        x = jax.device_put(X, lsh)
        y = jax.device_put(Y, lsh)
        w = jax.device_put(np.zeros((D,), np.float32), lrep)
        for _ in range(30):
            w = step(w, x, y)
        w_final = np.asarray(jax.device_get(w))
    print(json.dumps({
        "pid": pid,
        "n_devices_global": n_dev,
        "data_plane": data_plane,
        "w": [round(float(v), 6) for v in w_final],
        "err": round(float(np.abs(w_final - w_true).max()), 6),
    }), flush=True)
    distributed.shutdown()


if __name__ == "__main__":
    main()
