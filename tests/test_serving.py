"""Serving-tier correctness (ISSUE 8): batched-vs-sequential bit-identity
under ragged coalescing, KV-cache decode == full-recompute decode (exact
for greedy), deadline-miss shedding through the 429 path, multi-model
isolation, bucket-policy single source of truth (pad-up-not-retrace with
``serving.recompiles_total`` == 0 in steady state), and graceful drain on
a REAL SIGTERM reusing the r11 seam."""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (DeadlineExceededError, Generator,
                                        ModelRouter, ModelServer,
                                        QueueFullError, ServingModel)
from deeplearning4j_tpu.util import telemetry as tm
from deeplearning4j_tpu.util.compile_watcher import get_watcher
from deeplearning4j_tpu.zoo.bert import Bert

R = np.random.default_rng(7)


def _dense_net(buckets=(2, 4, 8), n_in=10, n_out=4, seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .batch_buckets(buckets).list()
            .layer(DenseLayer(n_in=n_in, n_out=24, activation="relu"))
            .layer(OutputLayer(n_in=24, n_out=n_out, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _decoder_net(vocab=43, max_length=32, seed=0):
    return Bert.tiny(causal=True, task="mlm", vocab_size=vocab,
                     max_length=max_length, hidden_dropout=0.0).init()


def _counter(name: str) -> float:
    tele = tm.get_telemetry()
    return sum(v for (n, _l), v in tele.counters.items() if n == name)


@pytest.fixture
def dense_model():
    net = _dense_net()
    model = ServingModel(net, "dense")
    model.warmup()
    return net, model


class TestBatchedBitIdentity:
    def test_ragged_coalescing_bit_identical(self, dense_model):
        """Three ragged requests (3+5+2 rows) coalesced into one bucketed
        batch must return EXACTLY what each request gets alone — the r8
        0-pad contract carried through the scheduler."""
        net, model = dense_model
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        sizes = (3, 5, 2)
        xs = [R.normal(size=(n, 10)).astype(np.float32) for n in sizes]
        sched = BatchScheduler(model, max_wait_ms=50.0)
        futs = [sched.submit(x) for x in xs]  # queued before the worker
        sched.start()                          # starts -> ONE coalesced batch
        got = [np.asarray(f.result(timeout=30)) for f in futs]
        sched.drain(timeout=10)
        assert sched.counts["completed"] == 3
        for x, g in zip(xs, got):
            assert np.array_equal(g, np.asarray(net.output(x)))

    def test_direct_execute_matches_sequential(self, dense_model):
        net, model = dense_model
        xs = [R.normal(size=(n, 10)).astype(np.float32) for n in (1, 4, 6)]
        batched, stats = model.execute(xs)
        assert stats["real_rows"] == 11
        for x, g in zip(xs, batched):
            assert np.array_equal(np.asarray(g), np.asarray(net.output(x)))

    def test_generate_coalesced_matches_sequential(self):
        net = _decoder_net()
        model = ServingModel(net, "dec", kind="generate",
                             bucketing=BucketingPolicy(
                                 batch_buckets=(1, 2, 4), seq_buckets=(8,)))
        model.warmup()
        prompts = [np.asarray(p, np.int32)
                   for p in ([1, 2, 3], [4, 5, 6, 7], [8, 9])]
        both, _ = model.execute(prompts, max_new_tokens=5)
        solo = [model.execute([p], max_new_tokens=5)[0][0] for p in prompts]
        assert list(both) == list(solo)


class TestKvCacheDecode:
    def test_greedy_cache_equals_full_recompute(self):
        """The acceptance bit: KV-cache decode == full-recompute decode,
        exact token-for-token under greedy."""
        net = _decoder_net()
        gen = Generator(net, batch_buckets=(1, 2, 4), prefill_buckets=(8, 16))
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]
        cached = gen.generate(prompts, max_new_tokens=8)
        recomputed = gen.generate_full_recompute(prompts, max_new_tokens=8)
        assert cached == recomputed
        assert all(len(r) == 8 for r in cached)

    def test_prompt_between_prefill_buckets_pads_up(self):
        net = _decoder_net()
        gen = Generator(net, batch_buckets=(1, 2), prefill_buckets=(4, 8))
        gen.warmup()
        w = get_watcher()
        with w.scope() as s:
            gen.generate([[1, 2, 3, 4, 5, 6]], max_new_tokens=3)  # len 6 -> 8
            assert s.traces == 0

    def test_prompt_above_largest_prefill_bucket_uses_max_length(self):
        """A prompt longer than the largest explicit prefill bucket pads
        up to max_length (the implicit final bucket warmup also primes)
        instead of tracing a fresh per-length executable."""
        net = _decoder_net(max_length=32)
        gen = Generator(net, batch_buckets=(1, 2), prefill_buckets=(8,))
        assert gen._prefill_len(6) == 8
        assert gen._prefill_len(13) == 32   # above bucket 8 -> max_length
        gen.warmup()  # primes 8 AND 32
        w = get_watcher()
        with w.scope() as s:
            for n in (9, 13, 20):  # distinct above-bucket lengths
                gen.generate([list(range(1, n + 1))], max_new_tokens=2)
            assert s.traces == 0
        # cached decode still equals recompute at the max_length shape
        prompts = [list(range(1, 14))]
        assert gen.generate(prompts, max_new_tokens=4) == \
            gen.generate_full_recompute(prompts, max_new_tokens=4)

    def test_decode_compile_once(self):
        net = _decoder_net()
        gen = Generator(net, batch_buckets=(1, 2), prefill_buckets=(8,))
        gen.generate([[1, 2, 3]], max_new_tokens=4)  # traces prefill+decode
        w = get_watcher()
        with w.scope() as s:
            gen.generate([[5, 6]], max_new_tokens=6)   # same buckets
            gen.generate([[7, 8, 9, 1]], max_new_tokens=3)
            assert s.traces == 0

    def test_temperature_sampling_deterministic_per_key(self):
        import jax

        net = _decoder_net()
        gen = Generator(net, batch_buckets=(1, 2), prefill_buckets=(8,))
        a = gen.generate([[1, 2, 3]], max_new_tokens=6, temperature=0.8,
                         key=jax.random.PRNGKey(3))
        b = gen.generate([[1, 2, 3]], max_new_tokens=6, temperature=0.8,
                         key=jax.random.PRNGKey(3))
        assert a == b
        toks = a[0]
        assert all(0 <= t < 43 for t in toks)

    def test_eos_trimming(self):
        net = _decoder_net()
        gen = Generator(net, batch_buckets=(1, 2), prefill_buckets=(8,))
        full = gen.generate([[1, 2, 3]], max_new_tokens=8)[0]
        eos = full[2]
        trimmed = gen.generate([[1, 2, 3]], max_new_tokens=8,
                               eos_id=eos)[0]
        assert trimmed == full[: full.index(eos) + 1]

    def test_rejects_non_causal(self):
        net = Bert.tiny(task="mlm", vocab_size=31, max_length=16,
                        hidden_dropout=0.0).init()  # bidirectional
        with pytest.raises(ValueError, match="causal"):
            Generator(net)


class TestBucketSourceOfTruth:
    def test_between_buckets_pads_up_no_retrace(self, dense_model):
        """A request size that falls between buckets pads up to the next
        bucket instead of tracing a new program; serving.recompiles_total
        stays 0 in steady state."""
        net, model = dense_model
        rec_before = _counter("serving.recompiles_total")
        w = get_watcher()
        with w.scope() as s:
            for n in (1, 3, 5, 7, 8):  # between-bucket + exact sizes
                results, stats = model.execute(
                    [R.normal(size=(n, 10)).astype(np.float32)])
                assert stats["recompiles"] == 0
            assert s.traces == 0
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        sched = BatchScheduler(model).start()
        sched.submit(R.normal(size=(3, 10)).astype(np.float32)
                     ).result(timeout=30)
        sched.drain(timeout=10)
        assert _counter("serving.recompiles_total") == rec_before

    def test_above_largest_bucket_chunks_no_retrace(self, dense_model):
        net, model = dense_model
        w = get_watcher()
        with w.scope() as s:
            x = R.normal(size=(21, 10)).astype(np.float32)  # > bucket 8
            results, stats = model.execute([x])
            assert s.traces == 0
        assert np.array_equal(np.asarray(results[0]),
                              np.asarray(net.output(x)))
        # 21 -> 8 + 8 + 5(->8): the plan never leaves the bucket set
        assert model.policy.plan_serving_batch(21) == [(8, 8), (8, 8),
                                                       (5, 8)]

    def test_plan_cap_bounds_padded_batch(self):
        """batch_limit caps the PADDED per-call batch (device memory):
        chunking targets the largest bucket under the cap; when no bucket
        fits the cap wins and chunks pass through unpadded."""
        pol = BucketingPolicy(batch_buckets=(2, 4, 8))
        assert pol.plan_serving_batch(6, cap=6) == [(4, 4), (2, 2)]
        assert all(p <= 6 for _t, p in pol.plan_serving_batch(23, cap=6))
        assert pol.plan_serving_batch(3, cap=1) == [(1, 1)] * 3  # no fit
        pow2 = BucketingPolicy(batch_buckets="pow2")
        assert all(p <= 12 for _t, p in pow2.plan_serving_batch(30, cap=12))

    def test_parallel_inference_shares_plan(self):
        """ParallelInference.output rides the same plan: an above-bucket
        request chunks to the largest bucket instead of tracing a fresh
        signature (the satellite fix in parallel/wrapper.py)."""
        from deeplearning4j_tpu.parallel.wrapper import ParallelInference

        net = _dense_net()
        policy = BucketingPolicy(batch_buckets=(2, 4, 8))
        pi = ParallelInference(net, bucketing=policy)
        pi.warmup(batch_sizes=policy.batch_buckets, input_shape=(10,))
        w = get_watcher()
        with w.scope() as s:
            x = R.normal(size=(19, 10)).astype(np.float32)
            out = pi.output(x)
            assert s.traces == 0
        assert out.shape == (19, 4)

    def test_batch_limit_bounds_padded_device_batch(self):
        """batch_limit is a device-memory bound: when it excludes every
        bucket, chunks pass through unpadded at the cap — the forward must
        never see a batch larger than batch_limit."""
        import jax

        from deeplearning4j_tpu.parallel.mesh import TrainingMesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelInference

        net = _dense_net()
        # 1-device mesh: mesh divisibility adds its own floor (>= one row
        # per device), which is the orthogonal constraint — the cap
        # contract is about bucketing rounding past batch_limit
        pi = ParallelInference(
            net, mesh=TrainingMesh(data=1, devices=jax.devices()[:1]),
            bucketing=BucketingPolicy(batch_buckets=(8, 16)),
            batch_limit=4)
        seen = []
        orig = pi._fwd
        pi._fwd = lambda p, s, x: (seen.append(x.shape), orig(p, s, x))[1]
        x = R.normal(size=(10, 10)).astype(np.float32)
        out = pi.output(x)
        assert out.shape == (10, 4)
        assert seen and all(sh[0] <= 4 for sh in seen), seen

    def test_router_load_generate_without_seq_buckets_boots(self, tmp_path):
        """router.load(kind='generate') on an archive whose conf has no
        seq_buckets (the common case) must warm on the pow2 fallback, not
        crash the server boot."""
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        net = _decoder_net(max_length=16)
        path = str(tmp_path / "decoder.zip")
        ModelSerializer.write_model(net, path)
        router = ModelRouter(name="genload")
        router.load("g", path, kind="generate")
        assert router.warmup() > 0
        fut = router.submit("g", np.asarray([1, 2, 3], np.int32),
                            lane="batch", max_new_tokens=3)
        assert len(fut.result(timeout=60)) == 3
        router.shutdown()

    def test_warmup_and_scheduler_one_policy_object(self, dense_model):
        _net, model = dense_model
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        sched = BatchScheduler(model)
        assert sched.max_batch == model.policy.largest_batch_bucket()
        if model.inference is not None:
            assert model.inference.bucketing is model.policy


class TestSheddingAndIsolation:
    def test_deadline_miss_sheds(self, dense_model):
        _net, model = dense_model
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        before = _counter("serving.shed_total")
        sched = BatchScheduler(model, max_wait_ms=1.0)
        fut = sched.submit(R.normal(size=(2, 10)).astype(np.float32),
                           deadline_ms=-1)  # already expired
        sched.start()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=30)
        sched.drain(timeout=10)
        assert _counter("serving.shed_total") > before

    def test_queue_full_admission_control(self, dense_model):
        _net, model = dense_model
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        sched = BatchScheduler(model, queue_limit=2)  # worker NOT started
        x = R.normal(size=(1, 10)).astype(np.float32)
        sched.submit(x)
        sched.submit(x)
        with pytest.raises(QueueFullError):
            sched.submit(x)
        sched.shutdown()

    def test_multi_model_isolation(self):
        """One model's flood must not starve another model's priority
        lane: per-model schedulers make isolation structural."""
        slow_net = _decoder_net()
        fast_net = _dense_net()
        router = ModelRouter(name="iso")
        slow = ServingModel(slow_net, "slow", kind="generate",
                            bucketing=BucketingPolicy(
                                batch_buckets=(1,), seq_buckets=(8,)))
        fast = ServingModel(fast_net, "fast")
        router.register(slow, max_wait_ms=0.5, queue_limit=64)
        router.register(fast, max_wait_ms=0.5, queue_limit=64)
        router.warmup()
        flood = [router.submit(
            "slow", np.asarray([1, 2, 3], np.int32), lane="batch",
            max_new_tokens=12) for _ in range(8)]
        fut = router.submit("fast",
                            R.normal(size=(2, 10)).astype(np.float32))
        fut.result(timeout=30)  # completes while the flood is queued
        _m, slow_sched = router.get("slow")
        assert slow_sched.queue_depth() > 0, \
            "flood drained before the fast request — load too light to " \
            "prove isolation"
        for f in flood:
            f.result(timeout=120)
        router.shutdown()

    def test_interactive_lane_beats_batch_lane(self, dense_model):
        """Within one model, the interactive lane drains before queued
        batch-lane work."""
        _net, model = dense_model
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        sched = BatchScheduler(model, max_wait_ms=0.0)
        x = R.normal(size=(2, 10)).astype(np.float32)
        order = []
        batch_futs = [sched.submit(x, lane="batch") for _ in range(4)]
        inter = sched.submit(x, lane="interactive")
        for i, f in enumerate(batch_futs):
            f.add_done_callback(lambda _f, i=i: order.append(("b", i)))
        inter.add_done_callback(lambda _f: order.append(("i", 0)))
        sched.start()
        inter.result(timeout=30)
        for f in batch_futs:
            f.result(timeout=30)
        sched.drain(timeout=10)
        assert order[0] == ("i", 0), order


class TestRouterAndSerializer:
    def test_load_from_model_serializer(self, tmp_path):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer

        net = _dense_net(seed=5)
        path = str(tmp_path / "dense.zip")
        ModelSerializer.write_model(net, path)
        meta = ModelSerializer.peek_meta(path)
        assert meta["type"] == "MultiLayerNetwork"
        router = ModelRouter(name="loadtest")
        router.load("restored", path,
                    bucketing=BucketingPolicy(batch_buckets=(2, 4)))
        model, _sched = router.get("restored")
        model.warmup()
        x = R.normal(size=(3, 10)).astype(np.float32)
        fut = router.submit("restored", x)
        assert np.array_equal(np.asarray(fut.result(timeout=30)),
                              np.asarray(net.output(x)))
        router.shutdown()

    def test_unknown_model_raises(self):
        from deeplearning4j_tpu.serving import UnknownModelError

        router = ModelRouter(name="empty")
        with pytest.raises(UnknownModelError):
            router.submit("ghost", np.zeros((1, 4), np.float32))

    def test_status_lists_models(self, dense_model):
        _net, model = dense_model
        router = ModelRouter(name="status")
        router.register(model)
        st = router.status()
        assert "dense" in st["models"]
        assert st["models"]["dense"]["kind"] == "classify"
        router.shutdown()


def _post(url, obj, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:
            return e.code, {}


class TestHttpServer:
    def test_infer_shed_and_drain_on_sigterm(self):
        """The HTTP contract end-to-end: 200 with bit-identical outputs,
        deterministic 429 on an expired deadline, then a REAL SIGTERM
        drains gracefully (finish queued work, 503 afterwards) — the r11
        drain seam on the serving side."""
        net = _dense_net()
        router = ModelRouter(name="http")
        router.register(ServingModel(net, "dense"), max_wait_ms=1.0)
        server = ModelServer(router, port=0).start()
        try:
            x = R.normal(size=(3, 10)).astype(np.float32)
            code, body = _post(f"{server.url}/v1/models/dense/infer",
                               {"inputs": x.tolist()})
            assert code == 200
            pad = np.concatenate([x, np.zeros((1, 10), np.float32)])
            assert np.array_equal(
                np.asarray(body["outputs"], np.float32),
                np.asarray(net.output(pad))[:3].astype(np.float32))

            code, body = _post(f"{server.url}/v1/models/dense/infer",
                               {"inputs": x.tolist(), "deadline_ms": -1})
            assert code == 429
            assert body["error"] == "DeadlineExceededError"

            drains_before = _counter("serving.drains_total")
            os.kill(os.getpid(), signal.SIGTERM)
            assert server.wait_drained(timeout=30)
            assert _counter("serving.drains_total") == drains_before + 1
            code, _ = _post(f"{server.url}/v1/models/dense/infer",
                            {"inputs": x.tolist()})
            assert code == 503
            ok, checks = tm.get_telemetry().health_report()
            assert checks["serving.drained"]["ok"]
        finally:
            server.stop()

    def test_generate_route_and_healthz_section(self):
        net = _decoder_net()
        router = ModelRouter(name="http-gen")
        model = ServingModel(net, "dec", kind="generate",
                             bucketing=BucketingPolicy(
                                 batch_buckets=(1, 2), seq_buckets=(8,)))
        router.register(model, max_wait_ms=1.0)
        server = ModelServer(router, port=0).start()
        try:
            code, body = _post(
                f"{server.url}/v1/models/dec/generate",
                {"prompt_tokens": [1, 2, 3], "max_new_tokens": 4})
            assert code == 200
            gen_direct = model.generator.generate([[1, 2, 3]],
                                                  max_new_tokens=4)
            assert body["tokens"] == gen_direct

            r = urllib.request.urlopen(f"{server.url}/healthz", timeout=30)
            health = json.loads(r.read())
            assert "dec" in health["serving"]["models"]
            r = urllib.request.urlopen(f"{server.url}/metrics", timeout=30)
            text = r.read().decode()
            assert "serving_requests_total" in text
            assert "serving_recompiles_total" in text
        finally:
            server.stop()

    def test_drain_in_flight_requests_complete(self):
        """Queued work submitted before the drain signal completes (finish
        in-flight, the elastic contract)."""
        net = _dense_net()
        router = ModelRouter(name="drain2")
        sm = ServingModel(net, "dense")
        sm.warmup()
        from deeplearning4j_tpu.serving.scheduler import BatchScheduler

        sched = BatchScheduler(sm, max_wait_ms=5.0)
        xs = [R.normal(size=(2, 10)).astype(np.float32) for _ in range(5)]
        futs = [sched.submit(x) for x in xs]   # queued, worker not running
        sched.start()
        assert sched.drain(timeout=30)         # must FINISH, not fail them
        for x, f in zip(xs, futs):
            assert np.array_equal(np.asarray(f.result(timeout=1)),
                                  np.asarray(net.output(x)))
