"""UI server, environment flags, evaluation breadth (calibration/ROC-MC).

Reference test parity: deeplearning4j-ui server tests, Nd4jEnvironment
flag tests, and nd4j evaluation suites (SURVEY.md §2.2 J5/J19, §5.6)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.config import Environment, get_environment
from deeplearning4j_tpu.eval import EvaluationCalibration, ROCMultiClass
from deeplearning4j_tpu.util import InMemoryStatsStorage
from deeplearning4j_tpu.util.ui_server import UIServer


class TestEvaluationBreadth:
    def test_roc_multiclass(self, rng):
        n = 400
        true = rng.integers(0, 3, n)
        labels = np.eye(3, dtype=np.float32)[true]
        # informative scores: high prob on the true class most of the time
        scores = rng.uniform(0.0, 0.4, (n, 3)).astype(np.float32)
        scores[np.arange(n), true] += 0.6 * (rng.random(n) < 0.8)
        scores /= scores.sum(1, keepdims=True)
        roc = ROCMultiClass().eval(labels, scores)
        assert roc.calculate_average_auc() > 0.7
        assert 0 <= roc.calculate_auc(1) <= 1

    def test_calibration_perfectly_calibrated(self, rng):
        # construct predictions whose confidence == empirical accuracy
        ec = EvaluationCalibration(n_bins=10)
        n = 4000
        conf = rng.uniform(0.55, 0.95, n)
        correct = rng.random(n) < conf
        preds = np.zeros((n, 2), np.float32)
        preds[:, 0] = conf
        preds[:, 1] = 1 - conf
        labels = np.zeros((n, 2), np.float32)
        labels[np.arange(n), np.where(correct, 0, 1)] = 1.0
        ec.eval(labels, preds)
        assert ec.expected_calibration_error() < 0.06
        centers, acc, mean_conf, counts = ec.reliability_diagram()
        assert counts.sum() == n

    def test_calibration_overconfident(self, rng):
        ec = EvaluationCalibration(n_bins=10)
        n = 2000
        preds = np.tile(np.asarray([[0.95, 0.05]], np.float32), (n, 1))
        correct = rng.random(n) < 0.5  # actual accuracy 50%, confidence 95%
        labels = np.zeros((n, 2), np.float32)
        labels[np.arange(n), np.where(correct, 0, 1)] = 1.0
        ec.eval(labels, preds)
        assert ec.expected_calibration_error() > 0.3


class TestUIServer:
    def test_serves_charts_and_data(self):
        storage = InMemoryStatsStorage()
        for i in range(10):
            storage.put({"session": "s", "iteration": i, "epoch": 0,
                         "score": 1.0 / (i + 1), "iter_ms": 12.5})
        ui = UIServer(port=0)
        ui.attach(storage)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            html = urllib.request.urlopen(f"{base}/train").read().decode()
            assert "<svg" in html and "score" in html
            data = json.loads(urllib.request.urlopen(
                f"{base}/train/data").read())
            assert len(data) == 10
            assert data[0]["iteration"] == 0
        finally:
            ui.stop()

    def test_multi_session_browsing(self):
        """VertxUIServer session-browser parity (VERDICT r2 weak #5): every
        session gets its own page; the landing page links them and defaults
        to the newest."""
        storage = InMemoryStatsStorage()
        for sid, base_score in (("run_a", 1.0), ("run_b", 2.0)):
            for i in range(5):
                storage.put({"session": sid, "iteration": i, "epoch": 0,
                             "score": base_score / (i + 1)})
        ui = UIServer(port=0)
        ui.attach(storage)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            sessions = json.loads(urllib.request.urlopen(
                f"{base}/train/sessions").read())
            assert sessions == ["run_a", "run_b"]
            landing = urllib.request.urlopen(f"{base}/train").read().decode()
            assert "run_b" in landing and "/train/session/run_a" in landing
            page_a = urllib.request.urlopen(
                f"{base}/train/session/run_a").read().decode()
            assert "run_a" in page_a and "<svg" in page_a
            data_a = json.loads(urllib.request.urlopen(
                f"{base}/train/data?session=run_a").read())
            assert len(data_a) == 5
            assert all(r["session"] == "run_a" for r in data_a)
        finally:
            ui.stop()


class TestEnvironment:
    def test_flags_install_and_remove_hook(self, monkeypatch):
        from deeplearning4j_tpu.ops import registry

        Environment._instance = None
        env = get_environment()
        assert env.profiler() is None
        env.set_profiling(True)
        import jax.numpy as jnp

        registry.exec_op("add", jnp.ones(2), jnp.ones(2))
        assert env.profiler().invocations["add"] == 1
        env.set_profiling(False)
        assert env.profiler() is None
        Environment._instance = None

    def test_nan_panic_flag(self):
        from deeplearning4j_tpu.ops import registry
        from deeplearning4j_tpu.util.profiler import NaNPanicError
        import jax.numpy as jnp

        Environment._instance = None
        env = get_environment()
        env.set_nan_panic(True)
        try:
            with pytest.raises(NaNPanicError):
                registry.exec_op("log", jnp.asarray([-1.0]))
        finally:
            env.set_nan_panic(False)
            Environment._instance = None

    def test_env_var_defaults(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_COMPUTE_DTYPE", "bfloat16")
        monkeypatch.setenv("DL4J_TPU_VERBOSE", "true")
        env = Environment()
        assert env.default_compute_dtype == "bfloat16"
        assert env.verbose is True


def test_compute_dtype_env_default(monkeypatch):
    from deeplearning4j_tpu.nn import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn import InputType

    monkeypatch.setenv("DL4J_TPU_COMPUTE_DTYPE", "bfloat16")
    Environment._instance = None
    try:
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_in=2, n_out=2))
                .layer(OutputLayer(n_in=2, n_out=2))
                .set_input_type(InputType.feed_forward(2)).build())
        assert conf.compute_dtype == "bfloat16"
    finally:
        Environment._instance = None


class TestUISessionEdgeCases:
    def test_metacharacter_session_ids_escape_and_roundtrip(self):
        from urllib.parse import quote

        storage = InMemoryStatsStorage()
        sid = "a<b&c/d"
        storage.put({"session": sid, "iteration": 0, "epoch": 0, "score": 1.0})
        ui = UIServer(port=0)
        ui.attach(storage)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            landing = urllib.request.urlopen(f"{base}/train").read().decode()
            assert "a&lt;b&amp;c/d" in landing  # escaped, not injected
            assert "<b&c" not in landing
            page = urllib.request.urlopen(
                f"{base}/train/session/{quote(sid, safe='')}").read().decode()
            assert "1 records" in page or "score" in page
        finally:
            ui.stop()

    def test_newest_session_is_insertion_order(self):
        storage = InMemoryStatsStorage()
        for sid in ("run_9", "run_10"):  # lexicographic would pick run_9
            storage.put({"session": sid, "iteration": 0, "epoch": 0,
                         "score": 1.0})
        ui = UIServer(port=0)
        ui.attach(storage)
        try:
            landing = urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/train").read().decode()
            assert "Training overview — run_10" in landing
        finally:
            ui.stop()


class TestUIHistograms:
    def test_histograms_page_from_real_training(self):
        """DL4J model-page histogram parity (VERDICT r3 missing #5): train a
        tiny net with StatsListener(collect_histograms=True), fetch
        /train/histograms, find per-layer parameter AND update bars."""
        import numpy as np

        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        from deeplearning4j_tpu.util.stats import StatsListener

        conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        storage = InMemoryStatsStorage()
        net.add_listener(StatsListener(storage, session_id="histsess",
                                       collect_activations=True))
        rng = np.random.default_rng(0)
        from deeplearning4j_tpu.data import ArrayDataSetIterator

        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        net.fit(ArrayDataSetIterator(x, y, batch=16), epochs=3)

        recs = storage.records if hasattr(storage, "records") else None
        ui = UIServer(port=0)
        ui.attach(storage)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            overview = urllib.request.urlopen(f"{base}/train").read().decode()
            assert "/train/histograms" in overview
            page = urllib.request.urlopen(
                f"{base}/train/histograms").read().decode()
            assert "<rect" in page, "no histogram bars rendered"
            assert "Parameters" in page and "Updates" in page
            assert "Activations" in page  # DL4J model-page parity
            assert "layer0.W" in page
        finally:
            ui.stop()
        del recs


class TestCGActivationStats:
    def test_graph_activation_histograms(self):
        """collect_activations on a ComputationGraph: vertex-name keyed
        activation summaries with histograms."""
        import numpy as np

        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.nn import (ComputationGraph, InputType,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        from deeplearning4j_tpu.util.stats import StatsListener

        gb = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1))
              .graph_builder().add_inputs("in"))
        gb.add_layer("h", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
        gb.add_layer("out", OutputLayer(n_in=8, n_out=2, loss="mcxent",
                                        activation="softmax"), "h")
        gb.set_outputs("out")
        gb.set_input_types(InputType.feed_forward(4))
        net = ComputationGraph(gb.build()).init()
        storage = InMemoryStatsStorage()
        net.set_listeners(StatsListener(storage, session_id="cgact",
                                        collect_activations=True))
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        net.fit(ArrayDataSetIterator(x, y, batch=8), epochs=2)
        recs = [r for r in storage.records if r.get("activations")]
        assert recs, "no activation records"
        acts = recs[-1]["activations"]
        assert "h" in acts and "out" in acts
        assert "hist" in acts["h"]


class TestSystemPage:
    def test_system_page_serves_host_and_devices(self):
        """DL4J UI System-tab parity (round 5): host memory, process RSS,
        and the PJRT device table render; repeated loads grow the RSS
        sample history that drives the live chart."""
        import urllib.request

        from deeplearning4j_tpu.util.ui_server import _system_snapshot

        snap = _system_snapshot()
        assert snap.get("host_mem_total_mb", 0) > 0
        assert snap.get("process_rss_mb", 0) > 0
        assert isinstance(snap.get("devices"), list) and snap["devices"]

        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        try:
            base = f"http://127.0.0.1:{ui.port}"
            html = urllib.request.urlopen(f"{base}/train/system").read() \
                .decode()
            assert "System" in html and "Devices" in html
            assert "process_rss_mb" in html or "host_mem_total_mb" in html
            urllib.request.urlopen(f"{base}/train/system").read()
            assert len(ui._sys_history) == 2
            # overview links to the system page
            over = urllib.request.urlopen(f"{base}/train").read().decode()
            assert "/train/system" in over
        finally:
            ui.stop()
