"""Paged KV cache + speculative decoding + int8 serving (ISSUE 15).

The acceptance contracts: paged-cache greedy decode TOKEN-IDENTICAL to the
contiguous r13 cache (and the O(T²) recompute oracle) for ragged prompts
crossing page boundaries; speculative greedy TOKEN-IDENTICAL to
non-speculative greedy — including a draft that is always wrong (k
rejections per round); eos mid-speculation-window; temperature>0 falling
back to verify-consistent sampling; pool exhaustion as a first-class 429
shed with blocks freed and reused; int8 round-trip through ModelSerializer
archives within the pinned tolerance with the fp32 path bit-unchanged;
ONE decode executable serving mixed context lengths with 0 steady-state
recompiles."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.serving import (BatchScheduler, Generator,
                                        INT8_LOGIT_TOL, ModelRouter,
                                        PoolExhaustedError, ServingModel)
from deeplearning4j_tpu.util.compile_watcher import get_watcher
from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.zoo.bert import Bert

VOCAB = 43
MAXLEN = 32
BUCKETS = dict(batch_buckets=(1, 2, 4), prefill_buckets=(8, 16))

#: ragged prompts whose contexts CROSS page boundaries at block_size=4
#: (lengths 3/5/9 → 1/2/3 blocks before decoding even starts)
RAGGED = [[1, 2, 3], [4, 5, 6, 7, 8], [9, 10, 11, 12, 13, 14, 15, 16, 17]]


@pytest.fixture(scope="module")
def target_net():
    return Bert.tiny(causal=True, task="mlm", vocab_size=VOCAB,
                     max_length=MAXLEN, hidden_dropout=0.0).init()


@pytest.fixture(scope="module")
def draft_net():
    return Bert.draft(vocab_size=VOCAB, max_length=MAXLEN, seed=7).init()


@pytest.fixture(scope="module")
def gen_contiguous(target_net):
    return Generator(target_net, paged=False, **BUCKETS)


@pytest.fixture(scope="module")
def gen_paged(target_net):
    return Generator(target_net, paged=True, block_size=4, **BUCKETS)


@pytest.fixture(scope="module")
def gen_spec(target_net, draft_net):
    return Generator(target_net, paged=True, block_size=4,
                     draft_net=draft_net, spec_tokens=3, **BUCKETS)


@pytest.fixture(scope="module")
def ref_tokens(gen_contiguous):
    return gen_contiguous.generate(RAGGED, max_new_tokens=8)


class TestPagedIdentity:
    def test_paged_equals_contiguous_and_recompute(self, gen_paged,
                                                   gen_contiguous,
                                                   ref_tokens):
        """The acceptance bit: paged greedy == contiguous greedy == O(T²)
        recompute, token-for-token, on ragged page-boundary-crossing
        prompts."""
        paged = gen_paged.generate(RAGGED, max_new_tokens=8)
        assert paged == ref_tokens
        assert paged == gen_contiguous.generate_full_recompute(
            RAGGED, max_new_tokens=8)
        assert all(len(r) == 8 for r in paged)

    def test_blocks_freed_after_batch(self, gen_paged):
        pool = gen_paged.pool
        assert pool.free_blocks() == pool.num_blocks
        gen_paged.generate([[1, 2, 3, 4, 5]], max_new_tokens=4)
        assert pool.free_blocks() == pool.num_blocks

    def test_sampled_paged_equals_contiguous(self, gen_paged,
                                             gen_contiguous):
        """temperature>0: the paged loop consumes the same key stream, so
        sampled output is identical too (stream-exact)."""
        key = jax.random.PRNGKey(11)
        a = gen_paged.generate(RAGGED, max_new_tokens=6, temperature=0.7,
                               key=key)
        b = gen_contiguous.generate(RAGGED, max_new_tokens=6,
                                    temperature=0.7, key=key)
        assert a == b

    def test_one_executable_mixed_context_lengths(self, gen_paged):
        """ONE decode executable serves mixed context lengths: after
        warmup, batches at wildly different context lengths trace
        NOTHING."""
        gen_paged.warmup()
        w = get_watcher()
        with w.scope() as s:
            gen_paged.generate([[1, 2]], max_new_tokens=4)
            gen_paged.generate([[i % VOCAB for i in range(20)]],
                               max_new_tokens=4)
            gen_paged.generate(RAGGED, max_new_tokens=4)
        assert s.traces == 0, f"steady-state decode traced {s.traces}x"

    def test_eos_early_exit_frees_blocks_and_trims(self, gen_paged,
                                                   gen_contiguous):
        ref = gen_contiguous.generate([[1, 2, 3]], max_new_tokens=8)
        eos = ref[0][2]  # third generated token
        out = gen_paged.generate([[1, 2, 3]], max_new_tokens=8, eos_id=eos)
        want = ref[0][:ref[0].index(eos) + 1]
        assert out[0] == want
        assert gen_paged.pool.free_blocks() == gen_paged.pool.num_blocks


class TestSpeculative:
    def test_spec_greedy_token_identical(self, gen_spec, ref_tokens):
        stats = {}
        out = gen_spec.generate(RAGGED, max_new_tokens=8, stats=stats)
        assert out == ref_tokens
        rates = stats["draft_accept_rate"]
        assert len(rates) == len(RAGGED)
        assert all(r is not None and 0.0 <= r <= 1.0 for r in rates)
        assert stats["spec_rounds"] >= 1

    def test_self_draft_accepts_everything(self, target_net, ref_tokens):
        """draft == target: every proposal verifies, accept rate 1.0 and
        far fewer rounds than tokens."""
        gen = Generator(target_net, paged=True, block_size=4,
                        draft_net=target_net, spec_tokens=3, **BUCKETS)
        stats = {}
        out = gen.generate(RAGGED, max_new_tokens=8, stats=stats)
        assert out == ref_tokens
        assert stats["spec_accept_rate"] == 1.0
        # 1 prefill token + ceil(7 / 4) fully-accepted windows
        assert stats["spec_rounds"] <= 3

    def test_draft_always_wrong_still_identical(self, gen_spec,
                                                ref_tokens):
        """k rejections per round: a draft proposing (token+1) mod V —
        essentially never the target's argmax — still yields the exact
        greedy sequence, one token per round (the correction token is the
        target's own logits)."""
        draft = gen_spec.draft
        orig = draft._decode_jit
        try:
            def wrong(raw, caches, tokens, positions):
                return (jax.nn.one_hot((tokens + 1) % VOCAB, VOCAB),
                        caches)

            draft._decode_jit = wrong
            stats = {}
            out = gen_spec.generate(RAGGED, max_new_tokens=8, stats=stats)
        finally:
            draft._decode_jit = orig
        assert out == ref_tokens
        assert stats["spec_accept_rate"] <= 0.25  # wrong ~always

    def test_eos_mid_speculation_window(self, gen_spec, gen_contiguous):
        """eos landing INSIDE an accepted window trims exactly like the
        non-speculative path."""
        prompts = [RAGGED[0], RAGGED[1]]
        ref = gen_contiguous.generate(prompts, max_new_tokens=8)
        eos = ref[0][3]  # 4th token: mid-window at spec_tokens=3
        out = gen_spec.generate(prompts, max_new_tokens=8, eos_id=eos)
        want = [r[:r.index(eos) + 1] if eos in r else r for r in ref]
        assert out == want
        assert gen_spec.pool.free_blocks() == gen_spec.pool.num_blocks

    def test_temperature_falls_back_to_plain_sampling(self, gen_spec,
                                                      gen_contiguous):
        """The verify-consistent sampling satellite: temperature>0 on a
        speculating generator routes through the plain per-token loop —
        identical streams to the non-speculative path."""
        key = jax.random.PRNGKey(3)
        a = gen_spec.generate(RAGGED, max_new_tokens=6, temperature=0.9,
                              key=key)
        b = gen_contiguous.generate(RAGGED, max_new_tokens=6,
                                    temperature=0.9, key=key)
        assert a == b


class TestPoolExhaustion:
    def test_exhaustion_sheds_and_blocks_reused(self, target_net):
        """All-or-nothing admission: an over-pool batch sheds with nothing
        allocated, and the freed pool serves the next batch (block
        free/reuse after shed)."""
        gen = Generator(target_net, paged=True, block_size=4,
                        pool_blocks=4, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8,))
        with pytest.raises(PoolExhaustedError):
            gen.generate([[1] * 8, [2] * 8, [3] * 8], max_new_tokens=8)
        assert gen.pool.free_blocks() == gen.pool.num_blocks
        out = gen.generate([[1, 2, 3]], max_new_tokens=8)  # 3 blocks
        assert len(out[0]) == 8
        assert gen.pool.free_blocks() == gen.pool.num_blocks

    def test_scheduler_first_class_shed(self, target_net):
        """The r13 shed contract, new cause: PoolExhaustedError through
        the scheduler is a shed (429 + Retry-After via ShedError), with
        its own flight-recorder cause and per-lane counter — never an
        error, never a breaker outcome."""
        model = ServingModel(target_net, "small-pool", kind="generate",
                             bucketing="batch=1,2;seq=8", block_size=4,
                             pool_blocks=2)
        model.warmup()
        sched = BatchScheduler(model, max_wait_ms=1.0)
        sched.start()
        try:
            fut = sched.submit(np.asarray([1] * 8, np.int32),
                               max_new_tokens=20)  # needs 7 blocks > 2
            with pytest.raises(PoolExhaustedError):
                fut.result(timeout=30)
            assert sched.counts["shed_pool_exhausted"] == 1
            assert sched.counts["errors"] == 0
            assert sched.lane_counts["interactive"][
                "shed_pool_exhausted"] == 1
            rec = sched.flight.dump(last=1)[0]
            assert rec["status"] == "shed"
            assert rec["cause"] == "pool_exhausted"
            assert sched.breaker.state == "closed"
            # pool freed: a fitting request decodes fine afterwards
            fut2 = sched.submit(np.asarray([1, 2], np.int32),
                                max_new_tokens=4)
            assert len(fut2.result(timeout=30)) == 4
        finally:
            sched.shutdown()

    def test_auto_pool_grows_instead_of_shedding(self, target_net):
        """An AUTO-sized pool (no operator budget) must never refuse a
        batch the contiguous engine would have served: exhaustion grows
        the pool (review finding r20). A PINNED pool keeps the shed."""
        gen = Generator(target_net, paged=True, block_size=4,
                        batch_buckets=(1, 2, 4), prefill_buckets=(8,))
        # shrink the auto pool under the batch's need, keeping auto mode
        gen.pool = type(gen.pool)(gen.blocks, block_size=4, num_blocks=4,
                                  max_length=gen.max_length)
        assert gen._pool_auto
        out = gen.generate([[1] * 8, [2] * 8, [3] * 8],
                           max_new_tokens=8)  # needs 12 > 4 blocks
        assert all(len(r) == 8 for r in out)
        assert gen.pool.num_blocks >= 12
        assert gen.pool.free_blocks() == gen.pool.num_blocks

    def test_stream_accounting(self, target_net):
        gen = Generator(target_net, paged=True, block_size=4,
                        pool_blocks=24, batch_buckets=(1, 2, 4),
                        prefill_buckets=(8,))
        gen.generate(RAGGED, max_new_tokens=4)
        st = gen.pool.stats()
        assert st["peak_streams"] == 3
        assert st["streams"] == 0
        assert st["contiguous_stream_ceiling"] == (24 * 4) // MAXLEN


class TestInt8Serving:
    def test_resident_bytes_and_tolerance(self, target_net, gen_paged):
        """Acceptance: resident int8 bytes ≥3.5× below fp32, prefill
        logits within the pinned tolerance, decode runs end to end."""
        gen = Generator(target_net, paged=True, block_size=4,
                        quantize="int8", **BUCKETS)
        qp = gen._qp
        assert qp.fp32_bytes() / qp.resident_bytes() >= 3.5
        tokens = jnp.asarray(np.asarray([RAGGED[1] + [0] * 3], np.int32))
        lengths = jnp.asarray([5], jnp.int32)
        tables = jnp.zeros((1, gen.pool.max_blocks_per_stream), jnp.int32)
        ql, pools = gen._prefill_paged_jit(gen._raw_params(),
                                           gen.pool.pools, tokens,
                                           lengths, tables)
        gen.pool.pools = pools
        t2 = jnp.zeros((1, gen_paged.pool.max_blocks_per_stream),
                       jnp.int32)
        fl, fpools = gen_paged._prefill_paged_jit(
            gen_paged._raw_params(), gen_paged.pool.pools, tokens,
            lengths, t2)
        gen_paged.pool.pools = fpools
        assert float(jnp.max(jnp.abs(ql - fl))) <= INT8_LOGIT_TOL
        out = gen.generate(RAGGED, max_new_tokens=6)
        assert all(len(r) == 6 for r in out)

    def test_fp32_path_bit_unchanged(self, target_net, gen_paged,
                                     ref_tokens):
        """Quantization is strictly opt-in: building an int8 generator
        mutates nothing, and the fp32 generator's output is bit-unchanged
        next to it."""
        before = [np.asarray(x).copy()
                  for x in jax.tree_util.tree_leaves(target_net.params)]
        Generator(target_net, paged=True, block_size=4, quantize="int8",
                  **BUCKETS)
        after = jax.tree_util.tree_leaves(target_net.params)
        assert all(np.array_equal(b, np.asarray(a))
                   for b, a in zip(before, after))
        assert gen_paged.generate(RAGGED, max_new_tokens=8) == ref_tokens

    def test_archive_roundtrip(self, target_net, tmp_path):
        """int8 round-trip through ModelSerializer: archive ~4× smaller,
        the stored quantization adopted VERBATIM on load (bit-identical
        to the pre-save quantized serving), and plain restore dequantizes
        to a usable fp32 net."""
        fp32 = str(tmp_path / "m.zip")
        int8 = str(tmp_path / "m8.zip")
        ModelSerializer.write_model(target_net, fp32, save_updater=False)
        ModelSerializer.write_model(target_net, int8, quantize="int8")
        assert os.path.getsize(fp32) / os.path.getsize(int8) >= 3.5
        meta = ModelSerializer.peek_meta(int8)
        assert meta["quantize"] == "int8"

        mem = Generator(target_net, paged=True, block_size=4,
                        quantize="int8", batch_buckets=(1, 2),
                        prefill_buckets=(8,))
        want = mem.generate(RAGGED[:2], max_new_tokens=6)

        router = ModelRouter("int8-rt")
        try:
            router.load("q8", int8, kind="generate", quantize="int8",
                        bucketing="batch=1,2;seq=8", block_size=4)
            model, _ = router.get("q8")
            model.warmup()
            got, _ = model.execute(
                [np.asarray(p, np.int32) for p in RAGGED[:2]],
                max_new_tokens=6)
            assert list(got) == want
            # the archive's quantization was adopted, not recomputed
            assert model.generator._qp is not None
        finally:
            router.shutdown()

        # plain restore: a dequantized fp32 net, params within tolerance
        net2 = ModelSerializer.restore_model(int8)
        a = jax.tree_util.tree_leaves(target_net.params)
        b = jax.tree_util.tree_leaves(net2.params)
        for x, y in zip(a, b):
            x, y = np.asarray(x), np.asarray(y)
            if x.ndim >= 2 and x.size >= 256:
                scale = np.abs(x).max() / 127.0
                assert np.max(np.abs(x - y)) <= scale + 1e-6
            else:
                assert np.array_equal(x, y)

    def test_stale_int8_stash_not_served(self, target_net, tmp_path):
        """A net restored from an int8 archive and then MUTATED must not
        serve the stale archived quantization (review finding r20): the
        stash is validated against the live params and falls through to
        fresh quantization."""
        from deeplearning4j_tpu.serving.quantize import maybe_quantize

        path = str(tmp_path / "m8.zip")
        ModelSerializer.write_model(target_net, path, quantize="int8")
        net = ModelSerializer.restore_model(path)
        assert getattr(net, "_int8_archive", None) is not None
        qp0 = maybe_quantize(net, "int8")  # untouched: stash adopted
        assert np.array_equal(np.asarray(qp0.qleaves[0]),
                              np.asarray(net._int8_archive[1][0]))
        # mutate the live params — the stash is now stale
        leaves = jax.tree_util.tree_leaves(net.params)
        big = max(range(len(leaves)), key=lambda i: leaves[i].size)
        mutated = [np.asarray(l).copy() for l in leaves]
        mutated[big] = mutated[big] + 1.0
        net.params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(net.params), mutated)
        qp1 = maybe_quantize(net, "int8")
        deq = np.asarray(qp1.qleaves[big], np.float32) * qp1.scales[big]
        assert np.max(np.abs(deq - mutated[big])) <= float(
            np.abs(mutated[big]).max() / 127.0) + 1e-6

    def test_resident_bytes_no_host_copy(self, target_net):
        """resident_bytes reads .nbytes without np.asarray — it runs on
        every status poll (review finding r20)."""
        from deeplearning4j_tpu.serving.quantize import QuantizedParams

        qp = QuantizedParams.from_params(target_net.params).device_put()
        assert qp.resident_bytes() > 0
        assert qp.fp32_bytes() / qp.resident_bytes() >= 3.5

    @staticmethod
    def _dense_net(seed=0):
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Adam(1e-3))
                .batch_buckets((2, 4)).list()
                .layer(DenseLayer(n_in=12, n_out=48, activation="relu"))
                .layer(OutputLayer(n_in=48, n_out=5, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.feed_forward(12)).build())
        return MultiLayerNetwork(conf).init()

    def test_int8_classify_within_tolerance(self):
        """The classify leg: int8 ServingModel output within tolerance of
        the fp32 forward; the fp32 ServingModel stays bit-exact."""
        net = self._dense_net()
        x = np.random.default_rng(0).normal(size=(3, 12)).astype(np.float32)
        ref = np.asarray(net.output(x))

        q = ServingModel(net, "q-clf", quantize="int8")
        q.warmup()
        got, _ = q.execute([x])
        assert np.max(np.abs(np.asarray(got[0]) - ref)) <= INT8_LOGIT_TOL

        f = ServingModel(net, "f-clf")
        f.warmup()
        got32, _ = f.execute([x])
        assert np.array_equal(np.asarray(got32[0]), ref)

    def test_int8_classify_reload_serves_new_weights(self, tmp_path):
        """Rolling reload of an int8 classify model must swap the
        quantized residents WITH the net (review finding r20): the
        post-reload output tracks the NEW weights, not the old int8
        closure."""
        net_a = self._dense_net(seed=0)
        net_b = self._dense_net(seed=9)  # same topology, new weights
        path = str(tmp_path / "b.zip")
        ModelSerializer.write_model(net_b, path, save_updater=False)
        x = np.random.default_rng(1).normal(size=(3, 12)).astype(np.float32)

        router = ModelRouter("int8-reload")
        try:
            router.register(ServingModel(net_a, "clf", quantize="int8"),
                            start=False)
            model, _sched = router.get("clf")
            model.warmup()
            before, _ = model.execute([x])
            version = router.reload("clf", path)
            assert version == 2
            after, _ = model.execute([x])
            ref_b = np.asarray(net_b.output(x))
            assert np.max(np.abs(np.asarray(after[0]) - ref_b)) \
                <= INT8_LOGIT_TOL
            assert not np.array_equal(np.asarray(before[0]),
                                      np.asarray(after[0]))
        finally:
            router.shutdown()
