"""Request-scope serving observability (ISSUE 12, docs/OBSERVABILITY.md
#request-tracing--slos): request ids + phase spans on the shared trace
timebase, head-based sampling with the slow/shed/error always-keep, the
per-model flight recorder (+ crash-dump section), per-lane latency/shed
attribution, the SLO engine's burn-rate/budget math with the /healthz 503
flip, and a strict Prometheus text-format conformance check over the new
series (extending the r10 newline-escape regression)."""

import json
import os
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.bucketing import BucketingPolicy
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (DeadlineExceededError, ModelRouter,
                                        ModelServer, QueueFullError,
                                        ServingModel)
from deeplearning4j_tpu.serving.scheduler import (BatchScheduler,
                                                  FlightRecorder,
                                                  trace_sample_rate)
from deeplearning4j_tpu.util import slo
from deeplearning4j_tpu.util import telemetry as tm

R = np.random.default_rng(3)


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch):
    """Fresh, enabled registry per test; collectors saved/cleared/restored
    (the test_telemetry.py convention); SLO engine reset; full head
    sampling unless the test overrides DL4J_TPU_TRACE_SAMPLE itself."""
    tele = tm.get_telemetry()
    tele.reset()
    was = tele.enabled
    saved_collectors = list(tele._collectors)
    saved_flag = tm._defaults_installed
    tele._collectors.clear()
    tm._defaults_installed = False
    tele.enabled = True
    monkeypatch.setenv("DL4J_TPU_TRACE_SAMPLE", "1")
    slo.reset()
    yield tele
    slo.reset()
    tele.enabled = was
    tele._collectors[:] = saved_collectors
    tm._defaults_installed = saved_flag
    tele.reset()


def _dense_net(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-3))
            .batch_buckets((2, 4, 8)).list()
            .layer(DenseLayer(n_in=6, n_out=12, activation="relu"))
            .layer(OutputLayer(n_in=12, n_out=3, loss="mcxent",
                               activation="softmax"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture(scope="module")
def dense_model():
    model = ServingModel(_dense_net(), "dense")
    model.warmup()
    return model


def _events(tele, name=None):
    tele._fold_pending()  # hot-path spans stage off-ring until an export
    evs = [dict(e) for e in tele._events]
    return [e for e in evs if name is None or e["name"] == name]


def _x(n=3):
    return R.normal(size=(n, 6)).astype(np.float32)


class TestRequestIdsAndPhaseSpans:
    def test_request_id_honored_and_phases_ordered(self, dense_model,
                                                   _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        fut = sched.submit(_x(), request_id="rid-explicit")
        fut.result(timeout=30)
        sched.drain(timeout=10)
        tele = _clean_registry
        qw = _events(tele, "serving.request.queue_wait")
        bf = _events(tele, "serving.request.batch_fill")
        cp = _events(tele, "serving.request.compute")
        assert qw and bf and cp
        for e in qw + bf + cp:
            assert e["args"]["request_id"] == "rid-explicit"
            assert e["args"]["model"] == "dense"
            assert e["args"]["lane"] == "interactive"
        # phases tile the request's life on ONE wall timebase:
        # queue_wait ends where batch_fill starts, which ends where
        # compute starts
        assert qw[0]["ts"] + qw[0]["dur"] == bf[0]["ts"]
        assert bf[0]["ts"] + bf[0]["dur"] == cp[0]["ts"]
        assert cp[0]["args"]["rows"] == 3
        assert cp[0]["args"]["bucket"] == 4  # 3 rows -> bucket 4

    def test_generated_id_unique_and_recorded(self, dense_model,
                                              _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        futs = [sched.submit(_x(1)) for _ in range(3)]
        for f in futs:
            f.result(timeout=30)
        sched.drain(timeout=10)
        ids = {r["id"] for r in sched.flight.dump()}
        assert len(ids) == 3 and all(len(i) == 12 for i in ids)

    def test_worker_thread_rows_and_nesting(self, dense_model,
                                            _clean_registry):
        """ISSUE 12 satellite: scheduler worker spans land on a
        model-id-named thread row in write_chrome_trace(), nesting the
        request phase spans (extends the r10 one-timebase merge test)."""
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        sched.submit(_x()).result(timeout=30)
        sched.drain(timeout=10)
        trace = _clean_registry.chrome_trace()
        evs = trace["traceEvents"]
        rows = {e["args"]["name"]: e["tid"] for e in evs
                if e.get("name") == "thread_name"}
        assert "serving-dense" in rows
        worker_tid = rows["serving-dense"]
        cycle = [e for e in evs if e["name"] == "serving.worker.batch_cycle"]
        batch = [e for e in evs if e["name"] == "serving.batch"]
        compute = [e for e in evs if e["name"] == "serving.request.compute"]
        assert cycle and batch and compute
        assert all(e["tid"] == worker_tid for e in cycle + batch + compute)
        assert cycle[0]["args"]["requests"] == 1
        # nesting chain: batch under the cycle, request phases under batch
        assert batch[0]["args"]["parent"] == "serving.worker.batch_cycle"
        assert compute[0]["args"]["parent"] == "serving.batch"
        # exported trace is Perfetto-loadable and relative-timed
        assert json.loads(json.dumps(trace))["traceEvents"]
        assert all(e["ts"] >= 0 for e in evs if e.get("ph") == "X")

    def test_exec_pad_and_device_spans(self, dense_model, _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        sched.submit(_x(3)).result(timeout=30)
        sched.drain(timeout=10)
        pad = _events(_clean_registry, "serving.exec.pad")
        dev = _events(_clean_registry, "serving.exec.device")
        assert pad and dev
        assert pad[0]["args"]["parent"] == "serving.batch"
        assert dev[0]["args"]["padded"] == 4


class TestDecodeTracing:
    @pytest.fixture(scope="class")
    def gen_model(self):
        from deeplearning4j_tpu.zoo.bert import Bert

        bert = Bert.tiny(causal=True, task="mlm", vocab_size=29,
                         max_length=16, hidden_dropout=0.0).init()
        model = ServingModel(bert, "dec", kind="generate",
                             bucketing=BucketingPolicy(batch_buckets=(1, 2),
                                                       seq_buckets=(8,)))
        model.warmup()
        return model

    def test_prefill_and_per_token_decode_spans(self, gen_model,
                                                _clean_registry):
        sched = BatchScheduler(gen_model, max_wait_ms=1.0).start()
        toks = sched.submit(np.asarray([1, 2, 3], np.int32),
                            lane="batch", max_new_tokens=5).result(timeout=60)
        sched.drain(timeout=10)
        assert len(toks) == 5
        prefill = _events(_clean_registry, "serving.generate.prefill")
        steps = _events(_clean_registry, "serving.generate.decode_token")
        assert len(prefill) == 1
        assert len(steps) == 4  # max_new_tokens - 1 decode steps
        assert [e["args"]["step"] for e in steps] == [1, 2, 3, 4]

    def test_tokens_per_sec_per_request(self, gen_model, _clean_registry):
        sched = BatchScheduler(gen_model, max_wait_ms=1.0).start()
        sched.submit(np.asarray([4, 5], np.int32), lane="batch",
                     max_new_tokens=3).result(timeout=60)
        sched.drain(timeout=10)
        snap = _clean_registry.snapshot()
        key = "serving.decode_tokens_per_sec{lane=batch,model=dec}"
        assert snap["histograms"][key]["count"] == 1
        assert snap["histograms"][key]["max"] > 0
        rec = sched.flight.dump()[-1]
        assert rec["tokens_per_sec"] > 0


class TestSampling:
    def test_rate_zero_disables_all_request_tracing(self, dense_model,
                                                    _clean_registry,
                                                    monkeypatch):
        monkeypatch.setenv("DL4J_TPU_TRACE_SAMPLE", "0")
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        sched.submit(_x()).result(timeout=30)
        shed = sched.submit(_x(), deadline_ms=-1)
        with pytest.raises(DeadlineExceededError):
            shed.result(timeout=30)
        sched.drain(timeout=10)
        assert not _events(_clean_registry, "serving.request.queue_wait")
        assert not _events(_clean_registry, "serving.request.compute")
        # the flight recorder is independent of sampling: both landed
        statuses = [r["status"] for r in sched.flight.dump()]
        assert sorted(statuses) == ["ok", "shed"]
        assert all(not r["traced"] for r in sched.flight.dump())

    def test_shed_always_kept_at_tiny_rate(self, dense_model,
                                           _clean_registry, monkeypatch):
        """Head sampling at a vanishing rate: a shed request's span is
        still emitted (slow/shed/error are always kept)."""
        monkeypatch.setenv("DL4J_TPU_TRACE_SAMPLE", "1e-9")
        sched = BatchScheduler(dense_model, max_wait_ms=1.0)
        fut = sched.submit(_x(), deadline_ms=-1, request_id="doomed")
        sched.start()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=30)
        sched.drain(timeout=10)
        qw = _events(_clean_registry, "serving.request.queue_wait")
        assert [e["args"]["request_id"] for e in qw] == ["doomed"]
        assert qw[0]["args"]["outcome"] == "shed:deadline"

    def test_rate_parse_and_memoization(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_TRACE_SAMPLE", raising=False)
        from deeplearning4j_tpu.serving.scheduler import DEFAULT_TRACE_SAMPLE

        assert trace_sample_rate() == DEFAULT_TRACE_SAMPLE
        monkeypatch.setenv("DL4J_TPU_TRACE_SAMPLE", "0.5")
        assert trace_sample_rate() == 0.5
        assert trace_sample_rate() == 0.5  # memoized path
        monkeypatch.setenv("DL4J_TPU_TRACE_SAMPLE", "7")   # clamped
        assert trace_sample_rate() == 1.0
        monkeypatch.setenv("DL4J_TPU_TRACE_SAMPLE", "junk")
        assert trace_sample_rate() == DEFAULT_TRACE_SAMPLE


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.record({"id": str(i)})
        assert len(fr) == 4
        assert [r["id"] for r in fr.dump()] == ["6", "7", "8", "9"]
        assert [r["id"] for r in fr.dump(last=2)] == ["8", "9"]

    def test_record_schema_and_phases(self, dense_model, _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0,
                               flight_capacity=8).start()
        sched.submit(_x(3), request_id="schema").result(timeout=30)
        sched.drain(timeout=10)
        rec = sched.flight.dump()[-1]
        assert rec["id"] == "schema" and rec["status"] == "ok"
        assert rec["lane"] == "interactive" and rec["rows"] == 3
        assert rec["bucket"] == 4 and rec["cause"] is None
        for k in ("queue_ms", "fill_ms", "compute_ms", "total_ms"):
            assert rec[k] is not None and rec[k] >= 0
        assert rec["total_ms"] >= rec["compute_ms"]
        assert rec["sampled"] is True and rec["traced"] is True

    def test_error_requests_recorded_with_cause(self, dense_model,
                                                _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0)
        fut = sched.submit(_x())
        # poison the batch: the model raises, the request records "error"
        orig = dense_model.execute
        dense_model.execute = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("boom"))
        try:
            sched.start()
            with pytest.raises(RuntimeError, match="boom"):
                fut.result(timeout=30)
        finally:
            dense_model.execute = orig
        sched.drain(timeout=10)
        rec = sched.flight.dump()[-1]
        assert rec["status"] == "error" and "boom" in rec["cause"]
        snap = _clean_registry.snapshot()
        assert snap["counters"][
            "serving.request_errors_total{lane=interactive,model=dense}"] == 1

    def test_router_debug_and_crash_dump_section(self, dense_model,
                                                 _clean_registry, tmp_path):
        from deeplearning4j_tpu.serving import UnknownModelError
        from deeplearning4j_tpu.util import CrashReportingUtil

        router = ModelRouter(name="fr")
        router.register(dense_model, max_wait_ms=1.0)
        router.submit("dense", _x(), request_id="dumped").result(timeout=30)
        recs = router.debug_requests("dense", last=5)
        assert recs and recs[-1]["id"] == "dumped"
        with pytest.raises(UnknownModelError):
            router.debug_requests("ghost")
        # the crash dump carries the flight recorder (sys.modules-guarded)
        p = tmp_path / "crash.json"
        CrashReportingUtil.write_crash_dump(_dense_net(), str(p),
                                            RuntimeError("postmortem"))
        info = json.loads(p.read_text())
        flat = info["serving_flight_recorder"]["fr"]["dense"]
        assert any(r["id"] == "dumped" for r in flat)
        router.shutdown()


class TestPerLaneAttribution:
    def test_stats_split_by_lane_with_shed_causes(self, dense_model,
                                                  _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0, queue_limit=2)
        ok = sched.submit(_x(), lane="interactive")
        doomed = sched.submit(_x(), lane="batch", deadline_ms=-1)
        with pytest.raises(QueueFullError):
            sched.submit(_x(), lane="batch")  # admission shed, batch lane
        sched.start()
        ok.result(timeout=30)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
        sched.drain(timeout=10)
        st = sched.stats()
        assert st["lanes"]["interactive"]["completed"] == 1
        assert st["lanes"]["interactive"]["shed"] == {}
        assert st["lanes"]["interactive"]["latency_p99_ms"] > 0
        assert st["lanes"]["batch"]["completed"] == 0
        assert st["lanes"]["batch"]["shed"] == {"deadline": 1,
                                                "queue_full": 1}
        assert st["lanes"]["batch"]["latency_p99_ms"] is None
        # combined totals unchanged (back-compat)
        assert st["completed"] == 1
        assert st["shed"] == {"deadline": 1, "queue_full": 1}

    def test_lane_labeled_gauges_and_shed_counters(self, dense_model,
                                                   _clean_registry):
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        sched.submit(_x(), lane="interactive").result(timeout=30)
        sched.submit(_x(), lane="batch").result(timeout=30)
        sched.drain(timeout=10)
        snap = _clean_registry.snapshot()
        g = snap["gauges"]
        assert "serving.latency_p99_seconds{lane=interactive,model=dense}" \
            in g
        assert "serving.latency_p99_seconds{lane=batch,model=dense}" in g
        assert "serving.latency_p99_seconds{model=dense}" in g  # combined
        assert snap["counters"][
            "serving.completed_total{lane=batch,model=dense}"] == 1

    def test_router_collect_metrics_per_lane(self, dense_model,
                                             _clean_registry):
        from deeplearning4j_tpu.serving.router import collect_metrics

        router = ModelRouter(name="lanes")
        router.register(dense_model, max_wait_ms=1.0)
        router.submit("dense", _x(), lane="interactive").result(timeout=30)
        rows = {(name, tuple(sorted(lab.items())))
                for name, lab, _v in collect_metrics()}
        assert ("serving.latency_p99_seconds",
                (("lane", "interactive"), ("model", "dense"))) in rows
        assert ("serving.flight_recorder_depth",
                (("model", "dense"),)) in rows
        router.shutdown()


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestSloEngine:
    def test_objective_validation(self):
        with pytest.raises(ValueError, match="unknown SLO kind"):
            slo.SloObjective("x", "p50", target=1.0)
        with pytest.raises(ValueError, match="availability target"):
            slo.SloObjective("x", "availability", target=1.5)
        with pytest.raises(ValueError, match="latency_p99 target"):
            slo.SloObjective("x", "latency_p99", target=-1)
        with pytest.raises(ValueError, match="window"):
            slo.SloObjective("x", "availability", target=0.99, windows=())

    def test_availability_burn_and_budget_math(self, _clean_registry):
        clock = _FakeClock()
        eng = slo.SloEngine(clock=clock)
        eng.register(slo.SloObjective(
            "avail", "availability", target=0.9, model="m1",
            windows=(10.0, 100.0)))
        # t=1000: baseline — 8 good, 0 bad
        tm.counter("serving.completed_total", 8, model="m1", lane="x")
        eng.evaluate()
        # t=1005: 2 shed arrive -> window bad fraction 2/2=1.0 over the
        # fresh traffic... plus 0 new good: burn = 1.0 / 0.1 = 10x
        clock.t += 5
        tm.counter("serving.shed_total", 2, model="m1", reason="deadline")
        doc = eng.evaluate()
        res = doc["objectives"][0]
        assert res["current"] == 0.8  # lifetime 8/(8+2)
        assert res["compliant"] is False
        w10 = res["windows"]["10s"]
        assert w10["bad"] == 2 and w10["good"] == 0
        assert w10["bad_fraction"] == 1.0
        assert w10["burn_rate"] == pytest.approx(10.0, rel=1e-3)
        assert res["budget_remaining"] < 0.0 or res["exhausted"]
        assert res["exhausted"] is True

    def test_window_baseline_is_last_sample_before_cutoff(
            self, _clean_registry):
        """Bad traffic recorded between the window start and the first
        in-window sample must still count: the baseline is the NEWEST
        sample at-or-before the cutoff, not the first one inside the
        window (which already has the bad events baked into its
        cumulative counters — the review-found early-age-out bug)."""
        clock = _FakeClock()
        eng = slo.SloEngine(clock=clock)
        eng.register(slo.SloObjective(
            "avail", "availability", target=0.9, model="mb",
            windows=(60.0,)))
        eng.evaluate()                          # t=1000: baseline (0, 0)
        clock.t += 50                           # events land at ~t=1005...
        tm.counter("serving.shed_total", 9, model="mb", reason="deadline")
        tm.counter("serving.completed_total", 1, model="mb", lane="x")
        res = eng.evaluate()["objectives"][0]   # ...sampled at t=1050
        assert res["exhausted"] is True
        clock.t += 12                           # t=1062: cutoff=1002 — the
        res = eng.evaluate()["objectives"][0]   # sheds are still in-window
        w = res["windows"]["60s"]
        assert w["bad"] == 9.0 and w["good"] == 1.0
        assert res["exhausted"] is True

    def test_burn_exactly_at_budget_is_not_exhausted(self, _clean_registry):
        """burn_rate == 1.0 is a service meeting its SLO to the decimal:
        it must NOT flip /healthz to 503 (strict < 0 on remaining)."""
        clock = _FakeClock()
        eng = slo.SloEngine(clock=clock)
        eng.register(slo.SloObjective(
            "edge", "latency_p99", target=100.0, model="me",
            budget=0.5, windows=(10.0,)))
        tm.gauge("serving.latency_p99_seconds", 0.050, model="me")
        eng.evaluate()                          # compliant sample
        tm.gauge("serving.latency_p99_seconds", 0.200, model="me")
        clock.t += 1
        res = eng.evaluate()["objectives"][0]   # 1 of 2 bad / budget 0.5
        assert res["windows"]["10s"]["burn_rate"] == 1.0
        assert res["budget_remaining"] == 0.0
        assert res["exhausted"] is False
        ok, checks = _clean_registry.health_report()
        assert checks.get("slo.edge", {}).get("ok") is not False

    def test_exhaustion_flips_health_fires_hooks_then_recovers(
            self, _clean_registry):
        clock = _FakeClock()
        eng = slo.SloEngine(clock=clock)
        eng.register(slo.SloObjective(
            "hooked", "availability", target=0.99, model="m2",
            windows=(10.0,)))
        breaches = []
        eng.on_breach(lambda name, detail: breaches.append((name, detail)))
        tm.counter("serving.completed_total", 1, model="m2", lane="x")
        eng.evaluate()
        clock.t += 1
        tm.counter("serving.shed_total", 5, model="m2", reason="queue_full")
        eng.evaluate()
        ok, checks = _clean_registry.health_report()
        assert not ok and checks["slo.hooked"]["ok"] is False
        assert "budget exhausted" in checks["slo.hooked"]["detail"]
        assert breaches and breaches[0][0] == "hooked"
        snap = _clean_registry.snapshot()
        assert snap["counters"][
            "slo.anomalies_total{type=budget_exhausted}"] == 1
        # the bad interval ages out of the window -> health recovers
        clock.t += 50
        tm.counter("serving.completed_total", 20, model="m2", lane="x")
        clock.t += 1
        eng.evaluate()
        clock.t += 9
        eng.evaluate()
        ok, checks = _clean_registry.health_report()
        assert checks["slo.hooked"]["ok"] is True
        assert _clean_registry.snapshot()["counters"][
            "slo.anomalies_total{type=budget_recovered}"] == 1
        assert len(breaches) == 1  # hook fires on the TRANSITION only

    def test_latency_objective_reads_worst_gauge(self, _clean_registry):
        clock = _FakeClock()
        eng = slo.SloEngine(clock=clock)
        eng.register(slo.SloObjective(
            "p99", "latency_p99", target=25.0, model="m3",
            windows=(10.0,), budget=0.5))
        tm.gauge("serving.latency_p99_seconds", 0.010, model="m3",
                 lane="interactive")
        doc = eng.evaluate()
        res = doc["objectives"][0]
        assert res["compliant"] is True and res["current"] == 10.0
        # a second, WORSE lane series: worst-case wins the filter
        tm.gauge("serving.latency_p99_seconds", 0.200, model="m3",
                 lane="batch")
        clock.t += 1
        res = eng.evaluate()["objectives"][0]
        assert res["current"] == 200.0 and res["compliant"] is False
        assert res["windows"]["10s"]["bad_fraction"] == 0.5  # 1 of 2 samples
        assert res["windows"]["10s"]["burn_rate"] == 1.0  # at budget

    def test_healthz_503_and_slo_section_via_http(self, _clean_registry,
                                                  monkeypatch):
        """The synthetic budget-exhausted case: /healthz flips to 503 on
        the SAME probe that sees the exhausted budget, and carries the slo
        section next to the serving one."""
        from deeplearning4j_tpu.util.ui_server import UIServer

        clock = _FakeClock()
        eng = slo.SloEngine(clock=clock)
        monkeypatch.setattr(slo, "_engine", eng)
        eng.register(slo.SloObjective(
            "synthetic", "availability", target=0.999, model="mz",
            windows=(10.0,)))
        tm.counter("serving.completed_total", 1, model="mz", lane="x")
        eng.evaluate()
        clock.t += 1
        tm.counter("serving.shed_total", 9, model="mz", reason="deadline")
        ui = UIServer(port=0)
        ui._start()
        try:
            base = f"http://127.0.0.1:{ui.port}"
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz")
            assert exc.value.code == 503
            doc = json.loads(exc.value.read().decode())
            assert doc["checks"]["slo.synthetic"]["ok"] is False
            sec = {o["name"]: o for o in doc["slo"]["objectives"]}
            assert sec["synthetic"]["exhausted"] is True
            # /slo route serves the same evaluation document
            r = urllib.request.urlopen(base + "/slo")
            names = [o["name"]
                     for o in json.loads(r.read().decode())["objectives"]]
            assert names == ["synthetic"]
        finally:
            ui.stop()

    def test_scrape_gauges_on_metrics(self, _clean_registry):
        slo.register(slo.SloObjective("scraped", "availability",
                                      target=0.99, model="ms"))
        text = _clean_registry.prometheus_text()
        assert 'dl4j_slo_compliant{slo="scraped"}' in text
        assert 'dl4j_slo_burn_rate{slo="scraped",window="60s"}' in text
        assert 'dl4j_slo_error_budget_remaining{slo="scraped"}' in text

    def test_duplicate_and_reset(self, _clean_registry):
        slo.register(slo.SloObjective("dup", "availability", target=0.9))
        with pytest.raises(ValueError, match="already declared"):
            slo.register(slo.SloObjective("dup", "availability", target=0.9))
        slo.reset()
        slo.register(slo.SloObjective("dup", "availability", target=0.9))


# --------------------------------------------------------------------------
# Strict Prometheus text-format conformance (ISSUE 12 satellite): every
# line of prometheus_text() must parse under the exposition-format grammar,
# histograms must expose monotone cumulative _bucket{le=} + _sum + _count,
# and the new per-lane + SLO series ride along. Regression-protects the
# r10 newline-escape fix: an unescaped newline would fail the line parse.
# --------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})? "
    r"(?P<value>-?(?:[0-9.eE+-]+|inf|nan))$")
_LABEL_RE = re.compile(
    r'^(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\\n]|\\\\|\\"|\\n)*)"$')


def _parse_prometheus(text: str):
    """Strict text-format 0.0.4 parser: returns {series_name: [(labels,
    value)]}; raises AssertionError on any grammar violation."""
    series = {}
    typed = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                         r"(counter|gauge|histogram|summary|untyped)$", line)
            assert m, f"line {lineno}: bad comment {line!r}"
            typed[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: unparsable sample {line!r}"
        labels = {}
        raw = m.group("labels")
        if raw is not None:
            assert raw, f"line {lineno}: empty label braces"
            # split on commas OUTSIDE quoted values
            parts = re.findall(r'[a-zA-Z_][a-zA-Z0-9_]*='
                               r'"(?:[^"\\]|\\.)*"', raw)
            assert ",".join(parts) == raw, \
                f"line {lineno}: malformed label block {raw!r}"
            for part in parts:
                lm = _LABEL_RE.match(part)
                assert lm, f"line {lineno}: bad label pair {part!r}"
                labels[lm.group("key")] = lm.group("val")
        float(m.group("value"))  # must be a valid float
        series.setdefault(m.group("name"), []).append(
            (labels, float(m.group("value"))))
    return series, typed


class TestPrometheusConformance:
    def _loaded_text(self, dense_model):
        # serving series (per-lane), an SLO objective, a histogram, and
        # the r10 regression payload (escaped newline in a label value)
        sched = BatchScheduler(dense_model, max_wait_ms=1.0).start()
        sched.submit(_x(), lane="interactive").result(timeout=30)
        sched.submit(_x(), lane="batch").result(timeout=30)
        try:
            sched.submit(_x(), lane="batch", deadline_ms=-1).result(
                timeout=30)
        except DeadlineExceededError:
            pass
        sched.drain(timeout=10)
        slo.register(slo.SloObjective("conf", "availability", target=0.99,
                                      model="dense"))
        tm.counter("esc.total", 1, note='say "hi"\nline two',
                   path="C:\\tmp")
        return tm.install_default_collectors().prometheus_text()

    def test_full_scrape_parses_strictly(self, dense_model,
                                         _clean_registry):
        text = self._loaded_text(dense_model)
        series, typed = _parse_prometheus(text)
        # the new per-lane + SLO series are present and well-typed
        lat = series["dl4j_serving_latency_p99_seconds"]
        lanes = {lab.get("lane") for lab, _v in lat}
        assert {"interactive", "batch", None} <= lanes
        shed = series["dl4j_serving_shed_total"]
        assert any(lab.get("reason") == "deadline"
                   and lab.get("lane") == "batch" for lab, _v in shed)
        assert typed["dl4j_slo_burn_rate"] == "gauge"
        assert any(lab == {"slo": "conf", "window": "3600s"}
                   for lab, _v in series["dl4j_slo_burn_rate"])
        assert series["dl4j_esc_total"][0][0]["note"] == 'say \\"hi\\"\\nline two'

    def test_histogram_series_conform(self, dense_model, _clean_registry):
        text = self._loaded_text(dense_model)
        series, typed = _parse_prometheus(text)
        base = "dl4j_serving_request_latency_seconds"
        assert typed[base] == "histogram"
        # group buckets by their non-le labels; each group must be
        # monotone cumulative, end at +Inf, and match _count
        groups = {}
        for lab, v in series[base + "_bucket"]:
            key = tuple(sorted((k, x) for k, x in lab.items() if k != "le"))
            groups.setdefault(key, []).append((lab["le"], v))
        counts = {tuple(sorted(lab.items())): v
                  for lab, v in series[base + "_count"]}
        sums = {tuple(sorted(lab.items())): v
                for lab, v in series[base + "_sum"]}
        assert groups and set(groups) == set(counts) == set(sums)
        for key, buckets in groups.items():
            assert buckets[-1][0] == "+Inf"
            vals = [v for _le, v in buckets]
            assert vals == sorted(vals), f"non-monotone buckets for {key}"
            assert vals[-1] == counts[key]
            les = [float(le) for le, _v in buckets[:-1]]
            assert les == sorted(les)
